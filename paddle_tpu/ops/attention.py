"""Fused attention ops.

The reference snapshot has only non-flash fused attention with O(S^2) memory
(paddle/fluid/operators/fused/fused_attention_op.cu, SURVEY §5.7) and no
sequence parallelism. Here attention is a first-class fused op: a Pallas
flash-attention kernel on TPU (paddle_tpu/ops/pallas/flash_attention.py) with
an XLA reference path everywhere else, both differentiable. Layout follows
the paddle convention [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..core import random as _random
from ..distributed import mesh as _mesh


def _pool_shard(pool):
    """Pin a paged pool (or pool-shaped intermediate) to the serving
    head-sharding: [NB, bs, H, D] with H over mp (int8 scale pools
    [NB, bs, H] shard the same axis). No-op without a mesh or without
    an mp axis — the single-chip path is untouched. Under an mp mesh
    this is what keeps every pool scatter/gather SHARD-LOCAL: block
    index arithmetic only touches axis 0, heads never cross shards."""
    if pool.ndim == 4:
        return _mesh.shard_constraint(pool, None, None, "mp", None)
    if pool.ndim == 3:
        return _mesh.shard_constraint(pool, None, None, "mp")
    return pool


def _gathered_shard(view):
    """Pin a gathered [B, width, H, D] contiguous pool view to the same
    head-sharding as the pool it came from — the axis-0 block gather is
    shard-local by construction; this makes that choice explicit to the
    partitioner instead of hoping propagation picks it."""
    if view.ndim == 4:
        return _mesh.shard_constraint(view, "dp", None, "mp", None)
    if view.ndim == 3:
        return _mesh.shard_constraint(view, "dp", None, "mp")
    return view


def _use_pallas(q_shape, head_dim):
    import os
    force = os.environ.get("PADDLE_TPU_FLASH")  # "1"/"0" override for tuning
    if force == "0":
        return False
    if force != "1":   # unforced: require a TPU-class platform
        try:
            d = jax.devices()[0].platform
        except RuntimeError:
            return False
        if d not in ("tpu", "axon"):
            return False
    # MXU-friendly constraints (enforced even when forced — the override
    # opts into the KERNEL on a capable host, never into invalid shapes):
    # seq tiles into 128-row blocks; head_dim pads to the 128-lane boundary
    # inside the kernel wrapper. Measured on v5e: the kernel beats XLA's
    # attention ~1.5x at S=1024 d=64 even with the padding overhead.
    return head_dim % 8 == 0 and q_shape[1] % 128 == 0


def attention_reference(q, k, v, mask=None, is_causal=False, scale=None,
                        dropout_p=0.0, dropout_key=None, score_dtype=None):
    """Reference jnp attention on [B, S, H, D]; fp32 softmax accumulation.

    score_dtype: dtype the S×S logit/probability arrays take in HBM.
    Default float32 (exact). Passing the model dtype (bf16) HALVES the
    dominant O(S²) memory traffic of this path — the QK dot still
    accumulates in f32 and the softmax max/sum run in f32; only the stored
    logits/probs round to bf16 (same numerics class as bf16 weights).
    Measured on v5e ViT-L/16 B=32: the f32 score arrays are ~320 MB/layer
    of traffic, the single largest non-matmul cost of the XLA path."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    dt = q.dtype
    import os
    if os.environ.get("PADDLE_TPU_SCORE_F32") == "1":
        # advisor r3: models hard-wire score_dtype=model-dtype for the
        # measured HBM win; this env reverts EVERY attention to exact f32
        # stored scores for convergence-sensitivity checks without code
        # changes (the Pallas kernels are unaffected — their scores are
        # f32-in-VMEM always)
        score_dtype = None
    sdt = jnp.dtype(score_dtype) if score_dtype is not None else jnp.float32
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = (logits * scale).astype(sdt)
    neg = jnp.asarray(-1e30 if sdt == jnp.float32 else -3e38, sdt)
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(cmask, logits, neg)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, neg)
        else:
            logits = (logits.astype(jnp.float32)
                      + mask.astype(jnp.float32)).astype(sdt)
    if sdt == jnp.float32:
        probs = jax.nn.softmax(logits, axis=-1)
    else:
        m = jnp.max(logits.astype(jnp.float32), axis=-1, keepdims=True)
        p = jnp.exp(logits.astype(jnp.float32) - m).astype(sdt)
        l = jnp.sum(p.astype(jnp.float32), axis=-1, keepdims=True)
        probs = (p.astype(jnp.float32) / l).astype(sdt)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(dt), v,
                      preferred_element_type=jnp.float32).astype(dt)


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None,
                                 score_dtype=None):
    """Eager entry point on Tensors.

    score_dtype (beyond-reference knob): dtype for the stored S×S
    logits/probs on the non-flash path — pass the model dtype (bf16) to
    halve the O(S²) HBM traffic; f32 accumulation is kept either way.
    Measured wins on v5e: ViT-L +5 MFU points, Swin +17% img/s,
    BERT +14% tok/s (those models set it internally)."""
    mask_arr = attn_mask._data if isinstance(attn_mask, Tensor) else attn_mask
    dk = _random.split_key() if (dropout_p > 0.0 and training) else None
    use_flash = (mask_arr is None and (dropout_p == 0.0 or not training)
                 and _use_pallas(tuple(query._data.shape), query._data.shape[-1]))

    if use_flash:
        from .pallas.flash_attention import flash_attention

        def fn(q, k, v):
            return flash_attention(q, k, v, causal=is_causal, scale=scale)
        return apply_op("flash_attention", fn, [query, key, value])

    def fn(q, k, v):
        return attention_reference(q, k, v, mask=mask_arr, is_causal=is_causal,
                                   scale=scale, dropout_p=dropout_p if training else 0.0,
                                   dropout_key=dk, score_dtype=score_dtype)
    return apply_op("sdpa", fn, [query, key, value])


def functional_attention(q, k, v, *, is_causal=False, scale=None, mask=None,
                         score_dtype=None):
    """Pure-array attention for jitted model code: picks flash kernel on TPU,
    reference path elsewhere. Differentiable in both cases. An explicit mask
    (bool keep-mask or additive float, broadcastable to [B,H,Sq,Sk]) forces
    the reference path."""
    if mask is None and _use_pallas(tuple(q.shape), q.shape[-1]):
        from .pallas.flash_attention import flash_attention
        return flash_attention(q, k, v, causal=is_causal, scale=scale)
    # Padded-flash path: self-attention with an odd sequence length
    # zero-pads q/k/v up to the 128-row block boundary and masks padded
    # KEYS inside the kernel (kv_len). Padded q rows compute garbage that
    # is sliced off; their cotangent is zero so dk/dv stay exact.
    # Threshold: measured on v5e, at ViT scale (S=197) the pad/transpose
    # overhead LOSES to XLA's O(S²) path (40% vs 48% MFU end-to-end), so
    # the route only opens where the S² term dominates (S >= 512).
    s = q.shape[1]
    pad = (-s) % 128
    if (mask is None and not is_causal and pad and s >= 512
            and q.shape[1] == k.shape[1]
            and _use_pallas((q.shape[0], s + pad) + tuple(q.shape[2:]),
                            q.shape[-1])):
        from .pallas.flash_attention import flash_attention
        cfg = [(0, 0), (0, pad), (0, 0), (0, 0)]
        out = flash_attention(jnp.pad(q, cfg), jnp.pad(k, cfg),
                              jnp.pad(v, cfg), causal=False, scale=scale,
                              kv_len=s)
        return out[:, :s]
    return attention_reference(q, k, v, mask=mask, is_causal=is_causal,
                               scale=scale, score_dtype=score_dtype)


# ----------------------------------------------------- static KV-cache ops
def static_cache_update(buf, new, pos):
    """Write `new` [B, s, H, D] into the fixed buffer [B, L_max, H, D] at
    row cursor `pos` (the CacheKV-workspace write shared by
    GPTForCausalLM.generate_static and incubate FusedMultiHeadAttention).

    Eager calls (concrete pos) raise on overflow; under jit the caller
    owns capacity (lax.dynamic_update_slice would silently clamp).

    Works for any rank >= 2 with the row cursor on axis 1 (the int8 cache
    path stores per-row scales in a rank-3 [B, L_max, H] buffer)."""
    import jax.core as _core
    from jax import lax
    if not isinstance(pos, _core.Tracer):
        p = int(pos)
        if p + new.shape[1] > buf.shape[1]:
            raise ValueError(
                f"static KV cache overflow: pos {p} + {new.shape[1]} new "
                f"rows > L_max {buf.shape[1]}")
    idx = (jnp.int32(0), pos.astype(jnp.int32)) + \
        (jnp.int32(0),) * (buf.ndim - 2)
    return lax.dynamic_update_slice(buf, new.astype(buf.dtype), idx)


# ------------------------------------------------ int8 KV-cache (serving)
def quantize_kv(new):
    """Symmetric per-(batch, position, head) int8 quantization of K/V rows.

    new [B, s, H, D] -> (codes int8 [B, s, H, D], scale f32 [B, s, H]); the
    scale spans the head_dim axis, so dequant is one fused multiply on the
    attention read. Serving analog of the reference's cache-quant path in
    fused_multi_transformer_op.cu (CacheKV int8 rows + per-row scales)."""
    f = new.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    codes = jnp.clip(jnp.round(f / scale[..., None]), -127, 127)
    return codes.astype(jnp.int8), scale


def dequantize_kv(codes, scale, dtype):
    """codes int8 [B, L, H, D] * scale [B, L, H] -> [B, L, H, D] `dtype`."""
    return (codes.astype(jnp.float32)
            * scale[..., None].astype(jnp.float32)).astype(dtype)


def attention_q8_cache(q, k_codes, k_scale, v_codes, v_scale, mask):
    """Decode attention reading an int8 KV cache WITHOUT dequantized
    buffers in HBM.

    The per-(pos,head) scales factor OUT of both contractions:
      q·(c_k·s_k)^T = (q·c_k^T)·s_k        (s_k is constant over head_dim)
      sum_k p_k·(s_v_k·c_v_k) = sum_k (p_k·s_v_k)·c_v_k
    so the big [B, L, H, D] operands enter their dots as bare int8->bf16
    converts (fused into the operand read by XLA — measured: the
    multiply-form dequant instead materializes full-width copies and is
    ~1.4x SLOWER than bf16 caches) and the scale multiplies land on the
    tiny [B, H, s, L] score arrays. Softmax runs in f32 as everywhere
    else. Serving analog of fused_multi_transformer_op.cu's CacheKV-int8
    mode."""
    dt = q.dtype
    att_scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_codes.astype(dt),
                        preferred_element_type=jnp.float32)
    ksT = jnp.transpose(k_scale, (0, 2, 1))[:, :, None, :]   # [B,H,1,L]
    logits = logits * (ksT * att_scale)
    logits = jnp.where(mask, logits, jnp.asarray(-1e30, jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    vsT = jnp.transpose(v_scale, (0, 2, 1))[:, :, None, :]
    probs = (probs * vsT).astype(dt)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_codes.astype(dt),
                      preferred_element_type=jnp.float32).astype(dt)


def static_cache_update_q8(codes_buf, scale_buf, new, pos):
    """Quantize `new` K/V rows to int8 and write codes+scales at `pos`."""
    codes, scale = quantize_kv(new)
    return (static_cache_update(codes_buf, codes, pos),
            static_cache_update(scale_buf, scale, pos))


# ------------------------------------------------- paged KV cache (serving)
# Block-pool serving path (ISSUE 5; Ragged Paged Attention, arxiv
# 2604.15464): KV lives in a fixed [num_blocks, block, H, D] pool, each
# request owns a list of blocks named by an int32 block table, and ONE
# fixed-shape executable serves any mix of request lengths. Block 0 is the
# reserved TRASH block (inference/kv_cache.py) — table padding entries and
# out-of-budget writes land there, so the scatter updates below never need
# a mask and can never touch another request's blocks.

def paged_cache_write(pool, new, tables, lens):
    """Write one decode-step row per batch entry into its pool block.

    pool [NB, bs, H, D]; new [B, 1, H, D]; tables [B, MB] i32; lens [B]
    i32 = tokens already in each row's cache, so row b's new token lands at
    global position lens[b] → block tables[b, lens[b]//bs], offset
    lens[b]%bs. Rows past their table width clamp into their own last
    block (their outputs are already ignored by then); trash-table rows
    (dummy slots) write block 0."""
    nb, bs = pool.shape[0], pool.shape[1]
    li = lens.astype(jnp.int32)
    bidx = jnp.take_along_axis(tables.astype(jnp.int32),
                               (li // bs)[:, None], axis=1,
                               mode="clip")[:, 0]
    dest = bidx * bs + (li % bs)
    flat = pool.reshape((nb * bs,) + pool.shape[2:])
    flat = flat.at[dest].set(new[:, 0].astype(pool.dtype))
    return _pool_shard(flat.reshape(pool.shape))


def paged_prefill_write(pool, new, tables, start=None):
    """Write a whole (right-padded) prompt's K/V rows into pool blocks.

    new [B, S, H, D] holds the PADDED prompt projection; position p of row
    b goes to block tables[b, p//bs], offset p%bs. Padding columns beyond a
    row's allocated blocks hit table entries of 0 — the trash block — and
    padding columns inside the row's own reservation are plain garbage the
    attention masks exclude until decode overwrites them.

    `start` [B] int32 (prefix-cache suffix prefill, ISSUE 10) offsets row
    b's positions to start[b] + p — the suffix lands after the shared
    cached prefix. Padding positions past the TABLE WIDTH are routed to
    the trash block explicitly (clipping them into the last table entry
    would let a garbage pad column share a destination row with a real
    suffix column and scatter-order would decide who wins); positions
    can never reach the shared prefix blocks (start + p >= start >= the
    prefix end for all written columns)."""
    nb, bs = pool.shape[0], pool.shape[1]
    b, s = new.shape[0], new.shape[1]
    pos = jnp.arange(s, dtype=jnp.int32)[None, :]
    if start is not None:
        pos = pos + start.astype(jnp.int32)[:, None]
    slot = pos // bs
    bidx = jnp.take_along_axis(tables.astype(jnp.int32),
                               jnp.broadcast_to(slot, (b, s)),
                               axis=1, mode="clip")
    bidx = jnp.where(slot >= tables.shape[1], 0, bidx)  # trash, not clip
    dest = (bidx * bs + pos % bs).reshape(-1)
    flat = pool.reshape((nb * bs,) + pool.shape[2:])
    flat = flat.at[dest].set(
        new.reshape((b * s,) + new.shape[2:]).astype(pool.dtype))
    return _pool_shard(flat.reshape(pool.shape))


def paged_prefill_mask(s, lens):
    """[B, 1, S, S] keep-mask for prompt self-attention over a right-padded
    ragged batch: causal AND key column < the row's true length — exactly
    static_cache_mask's ragged form at pos=0 over a buffer the size of the
    prompt itself (one definition of the ragged-causal semantics)."""
    return static_cache_mask(s, s, jnp.int32(0), prompt_lens=lens,
                             prefill_cap=s)


def paged_attention_reference(q, k_pool, v_pool, tables, lens, *,
                              scale=None, score_dtype=None):
    """Pure-jnp ragged paged decode attention — the CPU/tier-1 path and
    the parity oracle for the Pallas kernel.

    q [B, 1, H, D] (single decode token per row); pools [NB, bs, H, D];
    tables [B, MB]; lens [B] = ATTENDABLE rows per batch entry (callers
    pass tokens-in-cache + 1 so the just-written token sees itself).
    Gathers each row's blocks into a contiguous [B, MB*bs, H, D] view and
    defers to `attention_reference` with the ragged keep-mask — same
    softmax/accumulation conventions as the static-cache path. Rows with
    lens == 0 (dummy batch slots) produce garbage, not NaN: the masked
    softmax degrades to uniform, and callers drop those rows."""
    if q.shape[1] != 1:
        raise ValueError(f"paged_attention_reference serves single-token "
                         f"decode; got q seq len {q.shape[1]}")
    nb, bs = k_pool.shape[0], k_pool.shape[1]
    b, mb = tables.shape
    t = tables.astype(jnp.int32)
    k = _gathered_shard(
        jnp.take(k_pool, t, axis=0).reshape((b, mb * bs) + k_pool.shape[2:]))
    v = _gathered_shard(
        jnp.take(v_pool, t, axis=0).reshape((b, mb * bs) + v_pool.shape[2:]))
    col = jnp.arange(mb * bs, dtype=jnp.int32)[None, None, None, :]
    mask = col < lens.astype(jnp.int32)[:, None, None, None]
    return attention_reference(q, k, v, mask=mask, scale=scale,
                               score_dtype=score_dtype)


def _paged_gather(pool, tables):
    """Gather a row's blocks into a contiguous [B, MB*bs, ...] view —
    the XLA-visible reference form shared by every paged attention
    reference below (the Pallas kernels walk the table instead)."""
    nb, bs = pool.shape[0], pool.shape[1]
    b, mb = tables.shape
    t = tables.astype(jnp.int32)
    return _gathered_shard(
        jnp.take(pool, t, axis=0).reshape((b, mb * bs) + pool.shape[2:]))


def paged_prefix_mask(s, width, start):
    """[B, 1, S, width] keep-mask for SUFFIX prefill over a paged pool
    (prefix cache, ISSUE 10): query row i sits at global position
    start[b] + i and sees pool columns <= its own position — causal over
    the shared cached prefix plus the just-written suffix. Columns past
    the causal frontier (garbage padding writes, unwritten decode rows)
    are excluded by the same comparison."""
    col = jnp.arange(width, dtype=jnp.int32)[None, None, None, :]
    row = jnp.arange(s, dtype=jnp.int32)[None, None, :, None]
    return col <= (start.astype(jnp.int32)[:, None, None, None] + row)


def paged_prefix_attention_reference(q, k_pool, v_pool, tables, start, *,
                                     scale=None, score_dtype=None):
    """Suffix-prefill attention over a paged pool: q [B, S, H, D] holds
    the (right-padded) SUFFIX tokens at global positions start[b] + i;
    K/V for both the cached prefix and the suffix live in the pool
    already (prefix from the cache, suffix written by the caller).
    Padded query rows (i >= the row's suffix length) produce garbage the
    caller drops — same contract as paged_prefill_mask prefill."""
    k = _paged_gather(k_pool, tables)
    v = _paged_gather(v_pool, tables)
    mask = paged_prefix_mask(q.shape[1], k.shape[1], start)
    return attention_reference(q, k, v, mask=mask, scale=scale,
                               score_dtype=score_dtype)


# ------------------------------------------ int8 paged KV cache (serving)
# The static int8-KV trick (quantize_kv / attention_q8_cache: int8 codes +
# per-(position, head) f32 scales that FACTOR OUT of both contractions)
# ported to the paged pool (ISSUE 10): code pools are int8
# [NB, bs, H, D], scale pools f32 [NB, bs, H] — per-block factored
# scales, one scale row per pool row. Same pool holds ~2x the resident
# tokens; same write/gather plumbing as the fp paged path.

def paged_cache_write_q8(codes_pool, scale_pool, new, tables, lens):
    """Quantize one decode-step row per batch entry and scatter codes +
    scales into the pools (the int8 form of paged_cache_write)."""
    codes, scale = quantize_kv(new)
    return (paged_cache_write(codes_pool, codes, tables, lens),
            paged_cache_write(scale_pool, scale, tables, lens))


def paged_prefill_write_q8(codes_pool, scale_pool, new, tables,
                           start=None):
    """Quantize a (padded) prompt/suffix projection and bulk-write codes
    + scales into pool blocks (the int8 form of paged_prefill_write)."""
    codes, scale = quantize_kv(new)
    return (paged_prefill_write(codes_pool, codes, tables, start),
            paged_prefill_write(scale_pool, scale, tables, start))


def paged_attention_reference_q8(q, kc_pool, ks_pool, vc_pool, vs_pool,
                                 tables, lens):
    """Single-token decode attention over int8 paged pools — gathers
    codes + scales per row and defers to `attention_q8_cache`, so the
    numerics class is EXACTLY the static int8-KV path's (the parity
    oracle the tests pin). CPU/tier-1 path of paged_attention_q8."""
    if q.shape[1] != 1:
        raise ValueError(f"paged_attention_reference_q8 serves "
                         f"single-token decode; got q seq len {q.shape[1]}")
    kc = _paged_gather(kc_pool, tables)
    ks = _paged_gather(ks_pool, tables)
    vc = _paged_gather(vc_pool, tables)
    vs = _paged_gather(vs_pool, tables)
    col = jnp.arange(kc.shape[1], dtype=jnp.int32)[None, None, None, :]
    mask = col < lens.astype(jnp.int32)[:, None, None, None]
    return attention_q8_cache(q, kc, ks, vc, vs, mask)


def paged_prefix_attention_reference_q8(q, kc_pool, ks_pool, vc_pool,
                                        vs_pool, tables, start):
    """Suffix-prefill attention over int8 paged pools: the q8 form of
    paged_prefix_attention_reference (same causal-over-global-positions
    mask, factored-scale contraction math)."""
    kc = _paged_gather(kc_pool, tables)
    ks = _paged_gather(ks_pool, tables)
    vc = _paged_gather(vc_pool, tables)
    vs = _paged_gather(vs_pool, tables)
    mask = paged_prefix_mask(q.shape[1], kc.shape[1], start)
    return attention_q8_cache(q, kc, ks, vc, vs, mask)


def paged_attention_q8(q, kc_pool, ks_pool, vc_pool, vs_pool, tables,
                       lens):
    """int8 ragged paged decode attention: Pallas kernel on TPU (codes
    stream as int8 bytes, scales multiply the tiny per-block score
    column), jnp gather reference elsewhere — routed exactly like
    paged_attention."""
    if _use_paged_kernel():
        from .pallas.paged_attention import paged_attention_q8_kernel
        return paged_attention_q8_kernel(q, kc_pool, ks_pool, vc_pool,
                                         vs_pool, tables, lens)
    return paged_attention_reference_q8(q, kc_pool, ks_pool, vc_pool,
                                        vs_pool, tables, lens)


def _use_paged_kernel():
    """Kernel-vs-reference routing, mirroring `_use_pallas`:
    PADDLE_TPU_PAGED=0 forces the jnp reference, =1 forces the Pallas
    kernel (opting a capable host in), unforced requires a TPU-class
    platform. No shape constraints — the kernel is VPU-only."""
    import os
    force = os.environ.get("PADDLE_TPU_PAGED")
    if force == "0":
        return False
    if force == "1":
        return True
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except RuntimeError:
        return False


def paged_prefix_attention(q, k_pool, v_pool, tables, start, *, scale=None,
                           score_dtype=None):
    """Ragged MULTI-TOKEN paged attention (ISSUE 11; Ragged Paged
    Attention, arxiv 2604.15464): q [B, S, H, D] holds S query tokens per
    row at global positions start[b] + i, each attending every pool
    column <= its own position — causal over the cached prefix plus the
    window itself. One primitive serves suffix prefill after a partial
    prefix hit, chunked prefill, and speculative-decode verification;
    S = 1 with start = lens is the decode case. Pallas kernel on TPU
    (block-table walk, MXU-shaped per-block dots), jnp gather reference
    elsewhere — routed exactly like paged_attention."""
    if _use_paged_kernel():
        from .pallas.paged_attention import paged_prefix_attention_kernel
        return paged_prefix_attention_kernel(q, k_pool, v_pool, tables,
                                             start, scale=scale)
    return paged_prefix_attention_reference(q, k_pool, v_pool, tables,
                                            start, scale=scale,
                                            score_dtype=score_dtype)


def paged_prefix_attention_q8(q, kc_pool, ks_pool, vc_pool, vs_pool,
                              tables, start):
    """int8 ragged multi-token paged attention: the q8-pool form of
    paged_prefix_attention (factored-scale contraction math), routed
    kernel-vs-reference like every other paged op."""
    if _use_paged_kernel():
        from .pallas.paged_attention import paged_prefix_attention_q8_kernel
        return paged_prefix_attention_q8_kernel(q, kc_pool, ks_pool,
                                                vc_pool, vs_pool, tables,
                                                start)
    return paged_prefix_attention_reference_q8(q, kc_pool, ks_pool,
                                               vc_pool, vs_pool, tables,
                                               start)


def paged_attention(q, k_pool, v_pool, tables, lens, *, scale=None,
                    score_dtype=None):
    """Ragged paged decode attention: Pallas kernel on TPU (block-table
    indexed fetches, online softmax, nothing gathered to HBM), jnp gather
    reference elsewhere — selected exactly like flash_attention is."""
    if _use_paged_kernel():
        from .pallas.paged_attention import paged_attention_kernel
        return paged_attention_kernel(q, k_pool, v_pool, tables, lens,
                                      scale=scale)
    return paged_attention_reference(q, k_pool, v_pool, tables, lens,
                                     scale=scale, score_dtype=score_dtype)


def static_cache_mask(kv_capacity, s, pos, prompt_lens=None,
                      prefill_cap=None):
    """Bool keep-mask for fixed-buffer decode.

    Base form [1, 1, s, L_max]: query row i (global position pos+i) sees
    buffer columns <= pos+i — causal over the valid prefix, zeroed padding
    beyond the cursor.

    Ragged form (prompt_lens [B], prefill_cap int): prompts were RIGHT-
    padded to prefill_cap before prefill, so buffer rows in
    [prompt_lens[b], prefill_cap) hold garbage k/v — additionally mask
    them per batch row: a column is valid iff col < prompt_lens[b] (real
    prompt) or col >= prefill_cap (decoded tokens). One compiled program
    then serves ANY prompt length <= prefill_cap (VERDICT r3 #7; reference
    CacheKV analog: fused_multi_transformer_op.cu)."""
    col = jnp.arange(kv_capacity)[None, None, None, :]
    row = jnp.arange(s)[None, None, :, None]
    keep = col <= (pos.astype(jnp.int32) + row)
    if prompt_lens is not None:
        valid = ((col < prompt_lens.astype(jnp.int32)[:, None, None, None])
                 | (col >= prefill_cap))
        keep = keep & valid
    return keep
