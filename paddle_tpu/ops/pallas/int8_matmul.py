"""Weight-only int8 matmul for decode (dequantize IN-REGISTER, not in HBM).

Reference anchor: the weight-only int8 path of the reference's serving
transformer (paddle/fluid/operators/fused/fused_multi_transformer_op.cu) —
int8 weights stream from memory and widen inside the GEMM.

Why a kernel: autoregressive decode is weight-bandwidth-bound (~2.6 GB/step
bf16 at 1.3B). The r4 dequant-at-use path (int8 -> bf16 elementwise, then
the XLA dot) measured 1.31x where the byte ratio promises ~2x: XLA
materializes the widened weight in HBM, so the dot still READS full-width
bytes. Here the int8 tile is DMA'd to VMEM (half the bytes — the whole
win), widened in-register on the VPU, and fed straight to the MXU; the
per-channel scale multiplies the f32 accumulator, which is exact for
per-output-channel quantization ((x @ q) * s == x @ (q * s)).

Layouts: "kn" — q [K, N] with per-output-column scale s [N] (projection
weights [in, out]); "nk" — q [N, K] with per-row scale s [N] (the tied
embedding/LM-head table [V, H]). Forward-only (decode runs under no_grad).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _i0():
    return jnp.int32(0)


def _kernel(x_ref, q_ref, s_ref, o_ref, *, w_layout, out_dtype):
    x = x_ref[...]
    q = q_ref[...]
    qw = q.astype(jnp.bfloat16 if x.dtype == jnp.bfloat16 else jnp.float32)
    if w_layout == "kn":
        acc = jnp.dot(x, qw, preferred_element_type=jnp.float32)
    else:  # "nk": contract both last dims
        acc = lax.dot_general(x, qw, (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(out_dtype)


def _pick_tiles(m, k, n, itemsize, block_n):
    """(mt, bn) under the scoped-VMEM plan: 2x-buffered x tile (mt, K)
    + 2x-buffered int8 tile (K, bn) + f32 accumulator tile."""
    budget = 11 * 1024 * 1024
    for mt in (256, 128, 64, 32, 16, 8):
        if m % mt:
            continue
        for bn in (block_n, 256, 128):
            if n % bn:
                continue
            need = 2 * mt * k * itemsize + 2 * k * bn + 2 * mt * bn * 4
            if need <= budget:
                return mt, bn
    return 8, 128


def int8_matmul(x, q, s, *, w_layout="kn", block_n=512, interpret=False):
    """y = x @ dequant(q, s). x: [M, K]; see module doc for layouts.
    Returns [M, N] in x.dtype. Falls back to an XLA dequant-matmul when the
    platform/shape gate fails (numerics match: scale is per-output)."""
    m, k = x.shape
    n = q.shape[1] if w_layout == "kn" else q.shape[0]
    if not use_int8_matmul(m, k, n):
        # widen to x.dtype (bf16 on TPU), NOT f32: the fallback must not
        # read more weight bytes than the barrier'd bf16 dequant copy
        qw = q.astype(x.dtype)
        if w_layout == "kn":
            acc = jnp.dot(x, qw, preferred_element_type=jnp.float32)
        else:
            acc = lax.dot_general(x, qw, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return (acc * s).astype(x.dtype)
    mt, bn = _pick_tiles(m, k, n, x.dtype.itemsize, block_n)
    grid = (m // mt, n // bn)
    if w_layout == "kn":
        qspec = pl.BlockSpec((k, bn), lambda mi, ni: (_i0(), ni))
    else:
        qspec = pl.BlockSpec((bn, k), lambda mi, ni: (ni, _i0()))
    out = pl.pallas_call(
        functools.partial(_kernel, w_layout=w_layout, out_dtype=x.dtype),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((mt, k), lambda mi, ni: (mi, _i0())),
            qspec,
            pl.BlockSpec((1, bn), lambda mi, ni: (_i0(), ni)),
        ],
        out_specs=pl.BlockSpec((mt, bn), lambda mi, ni: (mi, ni)),
        interpret=interpret,
    )(x, q, s.reshape(1, n).astype(jnp.float32))
    return out


def use_int8_matmul(m, k, n, force=None):
    import os
    f = force if force is not None else os.environ.get(
        "PADDLE_TPU_INT8_MATMUL")
    if f in ("0", False):
        return False
    if f not in ("1", True):
        try:
            d = jax.devices()[0].platform
        except RuntimeError:
            return False
        if d not in ("tpu", "axon"):
            return False
    # K resident per program (int8 tile (K, bn) must fit VMEM comfortably)
    return m % 8 == 0 and k % 128 == 0 and n % 128 == 0 and k <= 16384


def int8_linear_nd(x, q, s, bias=None, *, w_layout="kn", interpret=False):
    """N-d wrapper: flattens leading dims of x to one matmul M."""
    lead = x.shape[:-1]
    k = x.shape[-1]
    y = int8_matmul(x.reshape(-1, k), q, s, w_layout=w_layout,
                    interpret=interpret)
    y = y.reshape(*lead, y.shape[-1])
    if bias is not None:
        y = y + bias
    return y
