"""Fused LM-head + softmax cross-entropy ("linear CE") for TPU.

Reference anchor: paddle/fluid/operators/collective/
c_softmax_with_cross_entropy_op.cu — the reference fuses softmax-CE over
sharded logits but still takes MATERIALIZED logits as input. Here the head
matmul itself lives inside the loss kernel, so the [T, V] logits never exist
in HBM in the forward pass at all.

Why this is the right TPU design (r4 profile): at GPT-1.3B flagship shape
(T = B·S = 6144 tokens, V = 50304, H = 2048) the chunked-XLA path streams
f32 chunk logits through HBM in the forward AND recomputes + re-streams them
under jax.checkpoint in the backward — ~30-37 ms of a 385 ms step, the
largest attackable non-MXU term on the board. The FLOP floor of the three
head matmuls (fwd, dx, dW) is ~19 ms at peak; the gap is pure logits traffic.

Forward (Pallas): grid (token_block, vocab_block), vocab innermost. One
x-tile [Bt, H] and one W-tile [Bv, H] are resident; the [Bt, Bv] f32 logits
tile lives only in registers/VMEM. Running max / sum-exp / gold-logit
accumulators persist in VMEM scratch across the vocab dimension (the same
online-softmax pattern as flash_attention.py). Outputs: per-token loss and
per-token logsumexp (the backward residual).

Backward (XLA matmuls, NO logits recompute chain): with lse saved, the
gradient is closed-form —
    dlogits[t, v] = g[t] * (exp(logits[t, v] - lse[t]) - 1{v == label[t]})
so each token chunk needs ONE bf16 matmul to rebuild the probability tile
fused with its epilogue, then dx = dlogits @ W and dW = dlogitsᵀ @ x run as
plain MXU matmuls. dlogits is materialized in bf16 (half the bytes of the
checkpoint path's f32 logits, with no second recompute pass); chunking keeps
its residency bounded.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _i0():
    # index-map literals must be i32 under x64 (Mosaic refuses i64)
    return jnp.int32(0)


def _fwd_kernel(lab_ref, x_ref, w_ref, loss_ref, lse_ref, m_sc, s_sc, g_sc,
                *, n_v, block_v, vocab, w_layout):
    vi = pl.program_id(1)

    @pl.when(vi == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        s_sc[...] = jnp.zeros_like(s_sc)
        g_sc[...] = jnp.zeros_like(g_sc)

    x = x_ref[...]
    w = w_ref[...]
    if w_layout == "vh":
        # logits tile = x [Bt,H] · wᵀ [H,Bv] — contraction on both lasts
        logits = lax.dot_general(x, w, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    else:  # "hv": w tile is [H, Bv]
        logits = lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
    col = vi * block_v + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    if vocab % block_v:
        # mask the ragged tail tile: out-of-vocab columns score -inf
        logits = jnp.where(col < vocab, logits, jnp.float32(_NEG))
    # gold-logit contribution: exactly one vocab tile contains each label
    lab = lab_ref[...]  # [Bt, 1] i32
    g_sc[...] += jnp.sum(jnp.where(col == lab, logits, jnp.float32(0.0)),
                         axis=1, keepdims=True)
    m_prev = m_sc[...]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    s_sc[...] = s_sc[...] * jnp.exp(m_prev - m_new) + jnp.sum(
        jnp.exp(logits - m_new), axis=1, keepdims=True)
    m_sc[...] = m_new

    @pl.when(vi == n_v - 1)
    def _finish():
        lse = m_sc[...] + jnp.log(s_sc[...])
        lse_ref[...] = lse
        loss_ref[...] = lse - g_sc[...]


def _pick_block_t(t, h, itemsize):
    """Largest token block dividing T that keeps the VMEM plan honest:
    x tile (2x buffered) + w tile (2x) + f32 logits tile + scratch.
    Measured on v5e at flagship shape (T=6144 H=2048 V=50304): bt=1024
    with bv=256 beats bt=512/bv=384 and bt=768 (fewer W re-streams; the
    W stream is the forward's bandwidth term)."""
    for bt in (1024, 768, 512, 384, 256, 128, 64, 32, 16, 8):
        if t % bt == 0 and (2 * bt * h * itemsize) <= 8 * 1024 * 1024:
            return bt
    return t


def _fwd(x, w, labels, *, block_t, block_v, w_layout, interpret):
    t, h = x.shape
    vocab = w.shape[0] if w_layout == "vh" else w.shape[1]
    n_t = t // block_t
    n_v = -(-vocab // block_v)
    if w_layout == "vh":
        wspec = pl.BlockSpec((block_v, h), lambda ti, vi: (vi, _i0()))
    else:
        wspec = pl.BlockSpec((h, block_v), lambda ti, vi: (_i0(), vi))
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, n_v=n_v, block_v=block_v, vocab=vocab,
                          w_layout=w_layout),
        out_shape=(jax.ShapeDtypeStruct((t, 1), jnp.float32),
                   jax.ShapeDtypeStruct((t, 1), jnp.float32)),
        grid=(n_t, n_v),
        in_specs=[
            pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, _i0())),
            pl.BlockSpec((block_t, h), lambda ti, vi: (ti, _i0())),
            wspec,
        ],
        out_specs=(pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, _i0())),
                   pl.BlockSpec((block_t, 1), lambda ti, vi: (ti, _i0()))),
        scratch_shapes=[pltpu.VMEM((block_t, 1), jnp.float32),
                        pltpu.VMEM((block_t, 1), jnp.float32),
                        pltpu.VMEM((block_t, 1), jnp.float32)],
        interpret=interpret,
    )(labels.reshape(t, 1).astype(jnp.int32), x, w)
    return loss[:, 0], lse[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _linear_ce(x, w, labels, block_t, block_v, w_layout, interpret,
               bwd_chunks):
    loss, _ = _fwd(x, w, labels, block_t=block_t, block_v=block_v,
                   w_layout=w_layout, interpret=interpret)
    return loss


def _linear_ce_fwd(x, w, labels, block_t, block_v, w_layout, interpret,
                   bwd_chunks):
    loss, lse = _fwd(x, w, labels, block_t=block_t, block_v=block_v,
                     w_layout=w_layout, interpret=interpret)
    return loss, (x, w, labels, lse)


def _linear_ce_bwd(block_t, block_v, w_layout, interpret, bwd_chunks,
                   res, g):
    import os
    impl = os.environ.get("PADDLE_TPU_LINEAR_CE_BWD", "onehot")
    x, w, labels, lse = res
    t, h = x.shape
    nc = bwd_chunks
    while t % nc:
        nc -= 1
    ct = t // nc
    dxs = []
    dw = None
    for c in range(nc):
        sl = slice(c * ct, (c + 1) * ct)
        xc, lc, sc, gc = x[sl], labels[sl], lse[sl], g[sl]
        if w_layout == "vh":
            logits = lax.dot_general(xc, w, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        else:
            logits = jnp.dot(xc, w, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - sc[:, None])
        if impl == "gather":
            # keep the [T, V] path a PURE matmul epilogue (p·g, one fused
            # convert) and handle the gold term outside it: the dx part is
            # a row-GATHER of W (g_t · W[label_t]); the dW part is a row-
            # SCATTER-add of g_t · x_t. Both touch T rows, not T·V.
            dlog = (p * gc[:, None]).astype(x.dtype)
            wl = w if w_layout == "vh" else w.T  # [V, H] view for gather
            gold_rows = wl[lc] * gc[:, None].astype(wl.dtype)
            dxs.append((jnp.dot(dlog, wl,
                                preferred_element_type=jnp.float32)
                        - gold_rows.astype(jnp.float32)).astype(x.dtype))
            dwc = lax.dot_general(dlog, xc, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
            dwc = dwc.at[lc].add(-(gc[:, None] * xc.astype(jnp.float32)))
            if w_layout != "vh":
                dwc = dwc.T
            dw = dwc if dw is None else dw + dwc
            continue
        if impl == "scatter":
            # gold term as a T-sized scatter-add instead of a [T, V]
            # iota-compare (the autodiff'd take_along_axis shape)
            dlog = (p * gc[:, None]).astype(x.dtype)
            dlog = dlog.at[jnp.arange(ct), lc].add(
                (-gc).astype(x.dtype), mode="drop")
        else:
            onehot = (lax.broadcasted_iota(jnp.int32, logits.shape, 1)
                      == lc[:, None].astype(jnp.int32))
            # bf16 dlogits: half the checkpoint path's f32 bytes
            dlog = ((p - onehot) * gc[:, None]).astype(x.dtype)
        if w_layout == "vh":
            dxs.append(jnp.dot(dlog, w, preferred_element_type=jnp.float32)
                       .astype(x.dtype))
            dwc = lax.dot_general(dlog, xc, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        else:
            dxs.append(lax.dot_general(dlog, w, (((1,), (1,)), ((), ())),
                                       preferred_element_type=jnp.float32)
                       .astype(x.dtype))
            dwc = lax.dot_general(xc, dlog, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dw = dwc if dw is None else dw + dwc
    dx = jnp.concatenate(dxs, axis=0) if len(dxs) > 1 else dxs[0]
    return dx, dw.astype(w.dtype), None


_linear_ce.defvjp(_linear_ce_fwd, _linear_ce_bwd)


def use_linear_ce(t, h, v):
    """Gate: TPU-class platform, MXU-friendly dims (mirrors use_fused_mha)."""
    import os
    force = os.environ.get("PADDLE_TPU_LINEAR_CE")
    if force == "0":
        return False
    if force != "1":
        try:
            d = jax.devices()[0].platform
        except RuntimeError:
            return False
        if d not in ("tpu", "axon"):
            return False
    return h % 128 == 0 and t % 8 == 0 and v >= 1024


def linear_cross_entropy(x, w, labels, *, w_layout="vh", block_t=None,
                         block_v=None, bwd_chunks=None, interpret=False):
    """Per-token softmax-CE of logits = x @ Wᵀ (w_layout="vh", W [V, H]) or
    x @ W (w_layout="hv", W [H, V]), with logits never materialized in the
    forward. x: [T, H]; labels: [T] int. Returns f32 [T] losses.
    """
    import os
    t, h = x.shape
    if block_t is None:
        block_t = int(os.environ.get("PADDLE_TPU_LINEAR_CE_BT", "0")) \
            or _pick_block_t(t, h, x.dtype.itemsize)
    if block_v is None:
        # bigger token blocks shrink the W-stream count; shrink the vocab
        # tile to keep the scoped-VMEM plan under the 16M chip limit
        block_v = int(os.environ.get("PADDLE_TPU_LINEAR_CE_BV", "0")) or (
            256 if block_t >= 1024 else 384)
    if bwd_chunks is None:
        bwd_chunks = int(os.environ.get("PADDLE_TPU_LINEAR_CE_BC", "2"))
    if t % block_t:
        raise ValueError(f"linear_cross_entropy: T={t} not divisible by "
                         f"block_t={block_t}")
    return _linear_ce(x, w, labels, int(block_t), int(block_v),
                      str(w_layout), bool(interpret), int(bwd_chunks))
