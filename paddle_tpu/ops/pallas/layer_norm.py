"""Pallas fused LayerNorm for TPU — single-HBM-pass forward AND backward.

Why this kernel exists (r3 profile, GPT-1.3B B=3 S=2048 on v5e): XLA's
autodiff of the naive mean/var formulation makes 3-4 passes over the
activation per LayerNorm backward (dgamma read, dbeta read, row-stat read,
dx combine) — ~200 MB of HBM traffic per [3,2048,2048] site where ~75 MB
suffices. At the measured ~180 GB/s effective bandwidth of the bench chip,
the 98 LN sites cost ~84 ms of a 387 ms step. This kernel does the textbook
one-pass-per-direction schedule:

  fwd:  read x once per row-block; s1/s2 accumulate in VREGs; write out
        (+ per-row mu, rsig for backward — O(R) extra, negligible)
  bwd:  read dy and x once per row-block; per-row a = Σ dy·γ·x̂ and
        b = Σ dy·γ feed dx in the same pass; dγ/dβ partials accumulate in
        a VMEM scratch across the (sequential) row-block grid and are
        written once at the last block.

The reference snapshot's layer_norm_kernel.cu (phi/kernels/gpu/) is the
capability anchor; the blockwise schedule here is TPU-native (8,128 tiles,
f32 accumulation, lane-dim reductions).

Numerics: statistics use one-pass E[x²]−E[x]² in f32 (same as Flax/Haiku
LN on TPU); outputs round to the input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _i0  # i32 index-map literal (Mosaic x64 rule)

DEFAULT_BLOCK_ROWS = 256


def _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rs_ref, *, eps, n):
    x = x_ref[...].astype(jnp.float32)
    s1 = jnp.sum(x, axis=-1, keepdims=True)
    s2 = jnp.sum(x * x, axis=-1, keepdims=True)
    mu = s1 / n
    var = jnp.maximum(s2 / n - mu * mu, 0.0)
    rs = jax.lax.rsqrt(var + eps)
    xhat = (x - mu) * rs
    out = xhat
    if g_ref is not None:
        out = out * g_ref[...].astype(jnp.float32)
    if b_ref is not None:
        out = out + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(o_ref.dtype)
    bq = x.shape[0]
    mu_ref[...] = jnp.broadcast_to(mu[:, 0][None, :], (8, bq))
    rs_ref[...] = jnp.broadcast_to(rs[:, 0][None, :], (8, bq))


def _bwd_kernel(dy_ref, x_ref, mu_ref, rs_ref, g_ref,
                dx_ref, dg_ref, db_ref, dg_sc, db_sc, *, n, n_blocks,
                has_gamma):
    bi = pl.program_id(0)

    @pl.when(bi == 0)
    def _init():
        dg_sc[...] = jnp.zeros_like(dg_sc)
        db_sc[...] = jnp.zeros_like(db_sc)

    dy = dy_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mu = mu_ref[...][0][:, None]
    rs = rs_ref[...][0][:, None]
    xhat = (x - mu) * rs
    if has_gamma:
        g = g_ref[...].astype(jnp.float32)
        dyg = dy * g
    else:
        dyg = dy
    a = jnp.sum(dyg * xhat, axis=-1, keepdims=True) / n
    b = jnp.sum(dyg, axis=-1, keepdims=True) / n
    dx_ref[...] = (rs * (dyg - xhat * a - b)).astype(dx_ref.dtype)
    dg_sc[...] += jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_sc[...] += jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(bi == n_blocks - 1)
    def _finish():
        dg_ref[...] = dg_sc[...].astype(dg_ref.dtype)
        db_ref[...] = db_sc[...].astype(db_ref.dtype)


def _pick_block(r):
    bq = min(DEFAULT_BLOCK_ROWS, r)
    while r % bq:
        bq //= 2
    return bq


def _ln_fwd(x2, gamma, beta, eps, interpret):
    r, h = x2.shape
    bq = _pick_block(r)
    nb = r // bq
    in_specs = [pl.BlockSpec((bq, h), lambda i: (i, _i0()))]
    args = [x2]
    if gamma is not None:
        in_specs.append(pl.BlockSpec((1, h), lambda i: (_i0(), _i0())))
        args.append(gamma.reshape(1, h))
    if beta is not None:
        in_specs.append(pl.BlockSpec((1, h), lambda i: (_i0(), _i0())))
        args.append(beta.reshape(1, h))

    def kern(*refs):
        if gamma is not None and beta is not None:
            x_ref, g_ref, b_ref, o_ref, mu_ref, rs_ref = refs
        elif gamma is not None:
            x_ref, g_ref, o_ref, mu_ref, rs_ref = refs
            b_ref = None
        elif beta is not None:
            x_ref, b_ref, o_ref, mu_ref, rs_ref = refs
            g_ref = None
        else:
            x_ref, o_ref, mu_ref, rs_ref = refs
            g_ref = b_ref = None
        _fwd_kernel(x_ref, g_ref, b_ref, o_ref, mu_ref, rs_ref,
                    eps=eps, n=float(h))

    out, mu, rs = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((r, h), x2.dtype),
                   jax.ShapeDtypeStruct((8, r), jnp.float32),
                   jax.ShapeDtypeStruct((8, r), jnp.float32)),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((bq, h), lambda i: (i, _i0())),
                   pl.BlockSpec((8, bq), lambda i: (_i0(), i)),
                   pl.BlockSpec((8, bq), lambda i: (_i0(), i))),
        interpret=interpret,
    )(*args)
    return out, mu, rs


def _ln_bwd(dy2, x2, mu, rs, gamma, interpret):
    r, h = x2.shape
    bq = _pick_block(r)
    nb = r // bq
    has_gamma = gamma is not None
    in_specs = [
        pl.BlockSpec((bq, h), lambda i: (i, _i0())),
        pl.BlockSpec((bq, h), lambda i: (i, _i0())),
        pl.BlockSpec((8, bq), lambda i: (_i0(), i)),
        pl.BlockSpec((8, bq), lambda i: (_i0(), i)),
    ]
    args = [dy2, x2, mu, rs]
    if has_gamma:
        in_specs.append(pl.BlockSpec((1, h), lambda i: (_i0(), _i0())))
        args.append(gamma.reshape(1, h))

    def kern(*refs):
        if has_gamma:
            dy_ref, x_ref, mu_ref, rs_ref, g_ref = refs[:5]
            dx_ref, dg_ref, db_ref, dg_sc, db_sc = refs[5:]
        else:
            dy_ref, x_ref, mu_ref, rs_ref = refs[:4]
            g_ref = None
            dx_ref, dg_ref, db_ref, dg_sc, db_sc = refs[4:]
        _bwd_kernel(dy_ref, x_ref, mu_ref, rs_ref, g_ref,
                    dx_ref, dg_ref, db_ref, dg_sc, db_sc,
                    n=float(h), n_blocks=nb, has_gamma=has_gamma)

    dx, dg, db = pl.pallas_call(
        kern,
        out_shape=(jax.ShapeDtypeStruct((r, h), dy2.dtype),
                   jax.ShapeDtypeStruct((1, h), jnp.float32),
                   jax.ShapeDtypeStruct((1, h), jnp.float32)),
        grid=(nb,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((bq, h), lambda i: (i, _i0())),
                   pl.BlockSpec((1, h), lambda i: (_i0(), _i0())),
                   pl.BlockSpec((1, h), lambda i: (_i0(), _i0()))),
        scratch_shapes=[pltpu.VMEM((1, h), jnp.float32),
                        pltpu.VMEM((1, h), jnp.float32)],
        interpret=interpret,
    )(*args)
    return dx, dg[0], db[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ln(x2, gamma, beta, eps, has_gamma, has_beta, interpret):
    out, _, _ = _ln_fwd(x2, gamma, beta, eps, interpret)
    return out


def _ln_vjp_fwd(x2, gamma, beta, eps, has_gamma, has_beta, interpret):
    out, mu, rs = _ln_fwd(x2, gamma, beta, eps, interpret)
    return out, (x2, mu, rs, gamma, beta)


def _ln_vjp_bwd(eps, has_gamma, has_beta, interpret, res, dy):
    x2, mu, rs, gamma, beta = res
    dx, dg, db = _ln_bwd(dy, x2, mu, rs, gamma, interpret)
    return (dx,
            dg.astype(gamma.dtype) if has_gamma else None,
            db.astype(beta.dtype) if has_beta else None)


_ln.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layer_norm(x, gamma=None, beta=None, eps: float = 1e-5,
                     interpret: bool = False):
    """LayerNorm over the LAST axis of x with optional affine params.

    x: [..., H]; gamma/beta: [H] or None. Returns same shape/dtype as x.
    Requires H % 128 == 0 and a row count divisible down to >=8-row
    blocks; callers fall back to the XLA formulation otherwise."""
    h = x.shape[-1]
    lead = x.shape[:-1]
    r = 1
    for d in lead:
        r *= int(d)
    x2 = x.reshape(r, h)
    out = _ln(x2, gamma, beta, float(eps),
              gamma is not None, beta is not None, bool(interpret))
    return out.reshape(x.shape)


def fused_layer_norm_supported(x_shape):
    """Static routing predicate shared with nn.functional.layer_norm.

    OPT-IN ONLY (PADDLE_TPU_FUSED_LN=1): on the v5e bench chip XLA's
    autodiff LN measured faster than this kernel (2.8 vs 3.4 ms fwd+bwd on
    [3,2048,2048]) — Mosaic's lowering of the f32 cast + two-axis reduce
    chain doesn't beat the fusion XLA already emits. Kept because the
    single-pass schedule is the right shape where relative costs differ.
    The platform gate keeps the env opt-in from routing a CPU host into a
    Mosaic compile that cannot succeed."""
    import os
    if os.environ.get("PADDLE_TPU_FUSED_LN") != "1":
        return False
    try:
        if jax.devices()[0].platform not in ("tpu", "axon"):
            return False
    except RuntimeError:
        return False
    if x_shape[-1] % 128 != 0:
        return False
    r = 1
    for d in x_shape[:-1]:
        r *= int(d)
    if r < 8 or r % 8 != 0:
        return False
    return True
