"""Fused short-sequence MHA with a cycling additive bias (Swin windows).

Reference anchor: the masked path of the reference's fused attention kernel
(paddle/fluid/operators/fused/fused_attention_op.cu with
operators/fused/fused_softmax_mask.cu:1) — attention logits get an additive
mask before the in-kernel softmax. The TPU shape of that capability here is
built for WINDOW attention: Swin runs thousands of 49-token windows per
image, and a (B·nW)-sized Pallas grid of 49-row programs is dispatch-bound
(measured r4). Instead, W_g windows are BATCHED into one program as a
length-S = W_g·49 sequence whose additive bias carries:

  - block-diagonal structure: -1e9 off the diagonal blocks (windows must
    not attend across each other),
  - the learned relative-position bias, tiled (differentiable — the kernel
    accumulates d(bias) so autodiff reaches the rel-bias table),
  - the static shifted-window masks.

The bias is PERIODIC over the batch: window-groups repeat the same layout
every image, so bias[r] with r = batch_index mod R serves the whole batch.
Grids keep the bias block VMEM-resident: forward (r, g, t) fetches each
(r, g) bias block once; backward (r, t, g) holds the (1, nh, S, S) dbias
output block resident across the inner sweep, accumulating per-program
contributions — Pallas TPU grids are sequential, so read-modify-write on
the resident output block is race-free.

Unlike fused_mha.py (packed [B,S,3F], which needs F % 128 == 0 for its
block slicing), q/k/v ride as SEPARATE arrays here: swin head counts (3,
6, 12, 24 at hd=32) give F = 96/192 that no packed block satisfies, while
a (1, S, G·hd) block over a [B, S, F] array is legal whenever G·hd is
128-aligned OR the full F. The packed<->split boundary is one XLA
slice/concat pair per call — noise at window sizes. Numerics conventions
are shared: bf16 dots, f32 accumulation, f32 softmax.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .fused_mha import _head, _softmax_f32, _i0


def _fwd_kernel(b_ref, q_ref, k_ref, v_ref, o_ref, *, nh, hd, G, scale):
    for j in range(G):
        q = _head(q_ref, j, hd)
        k = _head(k_ref, j, hd)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + b_ref[0, j].astype(jnp.float32)
        p = _softmax_f32(s)
        v = _head(v_ref, j, hd)
        o_ref[0, :, j * hd:(j + 1) * hd] = jnp.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _bwd_kernel(b_ref, q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref,
                db_ref, *, nh, hd, G, scale):
    t, gg = pl.program_id(1), pl.program_id(2)
    for j in range(G):
        q = _head(q_ref, j, hd)
        k = _head(k_ref, j, hd)
        v = _head(v_ref, j, hd)
        do = _head(do_ref, j, hd)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        s = s + b_ref[0, j].astype(jnp.float32)
        sigma = _softmax_f32(s)
        dsig = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        dv_ref[0, :, j * hd:(j + 1) * hd] = jnp.dot(
            sigma.astype(do.dtype).T, do,
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        r = jnp.sum(dsig * sigma, axis=-1, keepdims=True)
        ds_f32 = sigma * (dsig - r)          # grad wrt (scaled logits+bias)
        hslot = gg * G + j

        @pl.when(t == 0)
        def _init(hslot=hslot, ds_f32=ds_f32):
            db_ref[0, hslot] = ds_f32

        @pl.when(t > 0)
        def _acc(hslot=hslot, ds_f32=ds_f32):
            db_ref[0, hslot] += ds_f32

        ds = ds_f32.astype(q.dtype)
        dq_ref[0, :, j * hd:(j + 1) * hd] = (jnp.dot(
            ds, k, preferred_element_type=jnp.float32)
            * scale).astype(dq_ref.dtype)
        dk_ref[0, :, j * hd:(j + 1) * hd] = (jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32)
            * scale).astype(dk_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _mha_b(q, k, v, bias, nh, scale, G, interpret):
    return _fwd(q, k, v, bias, nh, scale, G, interpret)


def _fwd(q, k, v, bias, nh, scale, G, interpret):
    b, s, F = q.shape
    hd = F // nh
    R = bias.shape[0]
    n_groups = nh // G
    n_t = b // R
    spec = pl.BlockSpec((1, s, G * hd),
                        lambda r, g, t: (t * R + r, _i0(), g))
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, nh=nh, hd=hd, G=G, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, s, F), q.dtype),
        grid=(R, n_groups, n_t),
        in_specs=[
            pl.BlockSpec((1, G, s, s),
                         lambda r, g, t: (r, g, _i0(), _i0())),
            spec, spec, spec,
        ],
        out_specs=spec,
        interpret=interpret,
    )(bias, q, k, v)
    return out


def _vjp_fwd(q, k, v, bias, nh, scale, G, interpret):
    return _fwd(q, k, v, bias, nh, scale, G, interpret), (q, k, v, bias)


def _vjp_bwd(nh, scale, G, interpret, res, g_out):
    q, k, v, bias = res
    b, s, F = q.shape
    hd = F // nh
    R = bias.shape[0]
    n_groups = nh // G
    n_t = b // R
    spec = pl.BlockSpec((1, s, G * hd),
                        lambda r, t, g: (t * R + r, _i0(), g))
    dq, dk, dv, dbias = pl.pallas_call(
        functools.partial(_bwd_kernel, nh=nh, hd=hd, G=G, scale=scale),
        out_shape=(jax.ShapeDtypeStruct((b, s, F), q.dtype),
                   jax.ShapeDtypeStruct((b, s, F), q.dtype),
                   jax.ShapeDtypeStruct((b, s, F), q.dtype),
                   jax.ShapeDtypeStruct((R, nh, s, s), jnp.float32)),
        grid=(R, n_t, n_groups),
        in_specs=[
            pl.BlockSpec((1, G, s, s),
                         lambda r, t, g: (r, g, _i0(), _i0())),
            spec, spec, spec, spec,
        ],
        out_specs=(
            spec, spec, spec,
            pl.BlockSpec((1, nh, s, s), lambda r, t, g: (r, _i0(), _i0(),
                                                         _i0())),
        ),
        interpret=interpret,
    )(bias, q, k, v, g_out)
    return dq, dk, dv, dbias.astype(bias.dtype)


_mha_b.defvjp(_vjp_fwd, _vjp_bwd)


def fused_mha_bias(qkv, num_heads, bias, *, scale=None,
                   heads_per_program=None, interpret=False):
    """Batched-window attention with additive per-head bias.

    qkv: [B, S, 3·nh·hd] packed [q heads | k heads | v heads] (split into
        three arrays at the XLA boundary — one slice, one concat in vjp).
    bias: [R, nh, S, S] additive logits bias; program batch index p uses
        bias[p mod R] (B must be a multiple of R). Differentiable — the
        backward kernel accumulates d(bias) across the batch.
    Returns [B, S, nh·hd] context in the packed layout.
    """
    b, s, F3 = qkv.shape
    F = F3 // 3
    hd = F // num_heads
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    R, bnh, bs1, bs2 = bias.shape
    if bnh != num_heads or bs1 != s or bs2 != s:
        raise ValueError(f"fused_mha_bias: bias {bias.shape} does not match "
                         f"(R, {num_heads}, {s}, {s})")
    if b % R:
        raise ValueError(f"fused_mha_bias: batch {b} not a multiple of "
                         f"bias period {R}")
    G = heads_per_program or _pick_bias_group(num_heads, hd, s,
                                              qkv.dtype.itemsize)
    if num_heads % G or ((G * hd) % 128 and G != num_heads):
        # the (1, S, G·hd) blocks need a 128-aligned last dim unless the
        # block spans the full F (single group)
        raise ValueError(
            f"fused_mha_bias: heads_per_program={G} invalid for nh="
            f"{num_heads} hd={hd} (need nh%G==0 and (G*hd)%128==0, or "
            f"G==nh)")
    q, k, v = qkv[..., :F], qkv[..., F:2 * F], qkv[..., 2 * F:]
    return _mha_b(q, k, v, bias, int(num_heads), float(scale), int(G),
                  bool(interpret))


def _pick_bias_group(nh, hd, s, itemsize):
    """Largest head group fitting the VMEM plan: bias blocks (G,S,S) f32
    dominate — 2x-buffered input plus the resident (nh,S,S) f32 dbias
    output in the backward, plus ~4 (S,S) f32 ephemerals."""
    budget = 10 * 1024 * 1024
    fixed = nh * s * s * 4 + 4 * s * s * 4      # dbias block + ephemerals
    aligned = [G for G in range(nh, 0, -1)
               if nh % G == 0 and ((G * hd) % 128 == 0 or G == nh)]
    for G in aligned:
        need = fixed + 2 * G * s * s * 4 + 8 * 2 * s * G * hd * itemsize
        if need <= budget:
            return G
    return aligned[-1]


def use_fused_mha_bias(s, num_heads, head_dim):
    """Gate: TPU-class platform and a workable VMEM plan."""
    import os
    force = os.environ.get("PADDLE_TPU_FUSED_MHA_BIAS")
    if force == "0":
        return False
    if force != "1":
        try:
            d = jax.devices()[0].platform
        except RuntimeError:
            return False
        if d not in ("tpu", "axon"):
            return False
    if head_dim % 8 or s > 512:
        return False
    # bias+dbias resident VMEM must fit even at G=1
    return (num_heads * s * s * 4 + 6 * s * s * 4) <= 10 * 1024 * 1024
