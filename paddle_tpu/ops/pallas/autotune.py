"""Runtime kernel autotuning + cache — the PHI autotune analog.

Reference (SURVEY §2.1): phi/kernels/autotune/ — cache.h keyed kernel
configs + switch_autotune.cc measuring candidate algorithms at runtime,
gated on FLAGS_use_autotune. TPU-native version: Pallas kernel tile sizes
(the flash-attention bq/bk) are the tunable axis; candidates are timed
eagerly on the real device with synthetic data of the call's static
shape. Results persist to a JSON cache keyed by
(device kind, kernel, shape signature) so the cost is paid once per
machine/shape, like the reference's AlgorithmsCache.

Opt-in via paddle.set_flags({'FLAGS_flash_autotune': True}) — runtime
measurement costs one compile per candidate, which on remote-compile
setups is seconds each (the reference's conv autotune is opt-in for the
same reason).

Tracing rule: measurement happens ONLY on eager (concrete) calls — under
an outer jit everything would be staged into the caller's trace and
nothing actually runs, so flash_attention consults the cache during
tracing but never tunes there. Warm the cache with one eager call (or
tune_flash_blocks directly, using your PER-DEVICE shapes when training
SPMD — the kernel tile choice is per-shard).

MEASURED CAVEAT (v5e, r2 session): isolated-kernel timing can MISLEAD —
for GPT-1.3B S=2048 the tuner picks (256,512) which wins in isolation but
loses 6 MFU points inside the full training step (smaller K/V tiles
re-read HBM; the bandwidth they steal is invisible when the kernel runs
alone). `tune_in_step` closes this trap: it times candidates inside a
caller-supplied FULL step (bench.py wires it for the flagship via
PADDLE_TPU_BENCH_AUTOTUNE=step). The isolated `tune_flash_blocks` remains
for quick exploration.
"""
from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Tuple

_CACHE: Optional[Dict[str, list]] = None
_CACHE_PATH = os.environ.get(
    "PADDLE_TPU_AUTOTUNE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                 "autotune.json"))


def _load() -> Dict[str, list]:
    global _CACHE
    if _CACHE is None:
        try:
            with open(_CACHE_PATH) as f:
                _CACHE = json.load(f)
        except (OSError, ValueError):
            _CACHE = {}
    return _CACHE


def _save():
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        with open(_CACHE_PATH, "w") as f:
            json.dump(_CACHE, f, indent=1)
    except OSError:
        pass  # cache is an optimization, never an error


def clear_cache():
    global _CACHE
    _CACHE = {}
    try:
        os.remove(_CACHE_PATH)
    except OSError:
        pass


def flash_candidates(s_q: int, s_k: int) -> List[Tuple[int, int]]:
    """Tile candidates: powers of two dividing the sequence lengths,
    bounded by measured-VMEM-safe sizes (bq*bk <= 1024*1024 fits v5e's
    16M scoped vmem with d=128 bf16 operands; 2048-wide q blocks OOM —
    measured in the r2 bench session)."""
    qs = [b for b in (1024, 512, 256) if s_q % b == 0]
    ks = [b for b in (1024, 512, 256) if s_k % b == 0]
    out = [(bq, bk) for bq in qs for bk in ks]
    return out or [(min(1024, s_q), min(1024, s_k))]


def _cache_key(kernel: str, sig: Tuple) -> str:
    import jax
    dev = getattr(jax.devices()[0], "device_kind", "cpu")
    return f"{dev}|{kernel}|{'x'.join(str(s) for s in sig)}"


def _smallest(candidates):
    import math
    return min(candidates, key=lambda c: math.prod(c))


def cached_blocks(kernel: str, sig: Tuple) -> Optional[Tuple]:
    """Cache lookup only (no measurement) — safe during jit tracing."""
    hit = _load().get(_cache_key(kernel, sig))
    return tuple(hit) if hit is not None else None


def tune(kernel: str, sig: Tuple, candidates: List[Tuple],
         bench_fn, iters: int = 3) -> Tuple:
    """Generic measured selection with persistent caching.

    bench_fn(candidate) -> callable running the kernel once on synthetic
    data (compiled on first call); returns the fastest candidate. A
    candidate whose bench raises (tile too big for VMEM etc.) is skipped.
    """
    import jax

    cache = _load()
    key = _cache_key(kernel, sig)
    hit = cache.get(key)
    if hit is not None:
        return tuple(hit)

    if not candidates:
        raise ValueError(f"tune({kernel!r}): empty candidate list")
    import jax.core as _core
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            run = bench_fn(cand)
            out = run()
            if isinstance(jax.tree.leaves(out)[0], _core.Tracer):
                raise RuntimeError(
                    "tune() called under a jit trace: the benchmark would "
                    "be staged, not measured — call it eagerly")
            jax.block_until_ready(out)          # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                out = run()
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue                             # infeasible tile
        if dt < best_t:
            best, best_t = cand, dt
    if best is None:
        # nothing measured (all candidates failed): fall back WITHOUT
        # caching, so a transient failure cannot poison the persistent
        # cache. Candidate lists are ordered largest-tile-first, and the
        # dominant failure mode is VMEM OOM — so pick the SMALLEST
        # candidate (most likely to compile), not candidates[0].
        import logging
        smallest = _smallest(candidates)
        logging.getLogger(__name__).warning(
            "autotune(%s): every candidate failed to run; falling back to "
            "smallest tile %s (unmeasured)", kernel, smallest)
        return tuple(smallest)
    cache[key] = list(best)
    _save()
    return tuple(best)


_OVERRIDE = None


def override_blocks(bq: int, bk: int):
    """Context manager forcing flash tile sizes — the hook tune_in_step
    uses to rebuild a caller's step under each candidate."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        global _OVERRIDE
        prev = _OVERRIDE
        _OVERRIDE = (int(bq), int(bk))
        try:
            yield
        finally:
            _OVERRIDE = prev

    return cm()


def tune_in_step(kernel: str, sig: Tuple, candidates: List[Tuple],
                 build_step, iters: int = 2) -> Tuple:
    """Measured tile selection INSIDE a representative training step —
    closing the isolated-kernel trap documented above (r2: the isolated
    tuner's (256,512) pick lost 6 MFU points end-to-end because the HBM
    bandwidth small tiles steal is invisible when the kernel runs alone).

    build_step() -> run() must construct a FRESH step (fresh compile
    cache) and return a zero-arg callable that executes one full step AND
    fences on device completion (e.g. end with a host read like float(...)
    or jax.block_until_ready) — the tuner times run() wall-clock, and a
    fire-and-forget runner would measure async dispatch, not the step; the
    raw array case is fenced here as a safety net. Rebuilt once per
    candidate under override_blocks(cand), so every flash_attention call
    inside traces with that candidate's tiles. The winner persists in the
    same cache as tune() under key (device, kernel, sig) — reference
    contract: phi/kernels/autotune/switch_autotune.cc
    (measure-then-pick-then-cache).
    """
    import gc
    import logging

    cache = _load()
    key = _cache_key(kernel, sig)
    hit = cache.get(key)
    if hit is not None:
        return tuple(hit)

    log = logging.getLogger(__name__)
    best, best_t = None, float("inf")
    for cand in candidates:
        try:
            with override_blocks(*cand):
                import jax as _jax
                step = build_step()
                _jax.block_until_ready(step())   # compile (safety fence)
                _jax.block_until_ready(step())   # steady-state warm
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = step()
                _jax.block_until_ready(out)
                dt = (time.perf_counter() - t0) / iters
            log.info("tune_in_step(%s) %s: %.1f ms", kernel, cand, dt * 1e3)
        except Exception as e:
            log.info("tune_in_step(%s) %s: infeasible (%s)", kernel, cand,
                     str(e)[:120])
            dt = None
        finally:
            # each candidate holds a FULL model + optimizer state on
            # device; free before the next build (and before the caller's
            # own model allocates)
            step = None
            gc.collect()
        if dt is not None and dt < best_t:
            best, best_t = cand, dt
    if best is None:
        smallest = _smallest(candidates)
        log.warning("tune_in_step(%s): every candidate failed; falling "
                    "back to smallest tile %s", kernel, smallest)
        return tuple(smallest)
    cache[key] = list(best)
    _save()
    return tuple(best)


def tune_flash_blocks(b: int, s_q: int, s_k: int, h: int, d: int,
                      causal: bool, dtype) -> Tuple[int, int]:
    """Measure flash fwd+bwd across tile candidates for this shape."""
    import jax
    import jax.numpy as jnp

    def bench_fn(cand):
        bq, bk = cand
        from .flash_attention import flash_attention
        key = jax.random.PRNGKey(0)
        q = jax.random.normal(key, (b, s_q, h, d), jnp.float32).astype(dtype)
        k = jax.random.normal(key, (b, s_k, h, d), jnp.float32).astype(dtype)
        v = k

        def loss(q_, k_, v_):
            return flash_attention(q_, k_, v_, causal=causal,
                                   block_q=bq, block_k=bk).sum()

        f = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return lambda: f(q, k, v)

    return tune("flash_attention", (b, s_q, s_k, h, d, int(causal),
                                    str(dtype)),
                flash_candidates(s_q, s_k), bench_fn)
