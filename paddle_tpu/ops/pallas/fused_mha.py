"""Fused whole-sequence multi-head attention for SHORT sequences (TPU).

Reference anchor: paddle/fluid/operators/fused/fused_attention_op.cu — the
reference fuses QKV-transpose + QK^T + softmax + dropout + PV into one GPU
kernel precisely because at short S the cost is memory traffic and launch
overhead, not FLOPs. This is the TPU-native analog, built for the two model
classes the flash kernel serves poorly:

- ViT/Swin-class (S≈200, many heads): the streaming flash kernel's head-major
  [B*H, S, D] layout costs ~12 ms/step of pure transposes on ViT-L/16 B=32
  (r3 profile), and a (B·H,)-sized grid is 512 near-empty sequential programs.
- BERT-class (S≈512 + attention-probability dropout): XLA generates S² threefry
  bits per layer in HBM — measured ~20% MFU on bert-base MLM, the worst
  transformer number on the r3 board.

Design (differs from flash_attention.py, which streams K/V blocks):
- ONE program holds the ENTIRE sequence for a group of G heads. Grid is
  (B, nh/G); scores/probs (S×S f32) live only in VMEM — no online softmax, no
  logsumexp residual, no delta precompute.
- Layout is the PACKED projection output [B, S, nh·hd] (q, k, v each): the
  same array the qkv matmul produces and the out-projection consumes. Per-head
  lane slices are static offsets. Zero layout transposes in fwd or bwd.
- The backward pass is ONE kernel emitting dq, dk, dv together: with the full
  row resident it recomputes softmax directly (max/sum, not stored lse) and
  the softmax-vjp row term rowsum(dσ⊙σ) exactly, so the only residuals are
  the inputs themselves.
- Attention-probability dropout draws its mask from the Mosaic per-core PRNG
  (pltpu.prng_seed / prng_random_bits), seeded per (batch, head) — the S² of
  random bits never exist in HBM, and the backward regenerates bit-identical
  masks from the same seeds.

Numerics: dots run on bf16 operands with f32 accumulation
(preferred_element_type); softmax max/exp/sum and the probability matrix stay
f32 in VMEM. That is STRICTLY tighter than the XLA fallback path with
score_dtype=bf16 (which rounds stored probs to bf16 in HBM).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30

# scoped-VMEM budget to plan head-grouping against (chip limit is 16M;
# leave headroom for Mosaic's own temporaries)
_VMEM_BUDGET = 11 * 1024 * 1024


def _kv_mask_2d(s, kv_len):
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(col < kv_len, s, jnp.asarray(_NEG, s.dtype))


def _causal_mask_2d(s):
    row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    col = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(row >= col, s, jnp.asarray(_NEG, s.dtype))


def _softmax_f32(s):
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    return p / jnp.sum(p, axis=-1, keepdims=True)


def _drop_mask(seed_ref, bi, h, nh, shape, drop_p):
    """Regenerable keep-scale mask: 0 or 1/(1-p), f32.

    Seeded per (batch, global head) so forward and backward draw identical
    bits; uint32 threshold comparison gives P(drop) = drop_p to 2^-32."""
    pltpu.prng_seed(seed_ref[0, 0] + bi * nh + h)
    bits = pltpu.prng_random_bits(shape)
    bits = pltpu.bitcast(bits, jnp.uint32)
    thresh = jnp.uint32(min(int(drop_p * (2.0 ** 32)), 2 ** 32 - 1))
    inv = jnp.float32(1.0 / (1.0 - drop_p))
    return jnp.where(bits >= thresh, inv, jnp.float32(0.0))


def _head(ref, j, hd):
    return ref[0, :, j * hd:(j + 1) * hd]


def _fwd_kernel(seed_ref, *rest, nh, hd, G, scale, kv_len, causal, drop_p,
                per_row_lens=False):
    if per_row_lens:
        lens_ref, q_ref, k_ref, v_ref, o_ref = rest
    else:
        q_ref, k_ref, v_ref, o_ref = rest
    bi, g = pl.program_id(0), pl.program_id(1)
    for j in range(G):
        q = _head(q_ref, j, hd)
        k = _head(k_ref, j, hd)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_2d(s)
        if per_row_lens:
            # per-batch-row valid length (right-padded batches): the SMEM
            # scalar load by traced bi keeps the mask in-register
            s = _kv_mask_2d(s, lens_ref[bi, 0])
        elif kv_len is not None:
            s = _kv_mask_2d(s, kv_len)
        p = _softmax_f32(s)
        if drop_p > 0.0:
            p = p * _drop_mask(seed_ref, bi, g * G + j, nh, p.shape, drop_p)
        v = _head(v_ref, j, hd)
        o_ref[0, :, j * hd:(j + 1) * hd] = jnp.dot(
            p.astype(v.dtype), v,
            preferred_element_type=jnp.float32).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, *rest, nh, hd, G, scale, kv_len, causal, drop_p,
                per_row_lens=False):
    if per_row_lens:
        lens_ref, q_ref, k_ref, v_ref, do_ref, dqkv_ref = rest
    else:
        q_ref, k_ref, v_ref, do_ref, dqkv_ref = rest
    # dqkv_ref is the FULL (1, S, 3F) packed-gradient block, resident
    # across the head-group grid dim — each group writes its own column
    # span, so d(qkv) leaves the kernel already concatenated (the layout
    # the projection weight-grad consumes) with zero XLA copies. The span
    # start g·(G·hd) is a dynamic offset, so it must be provably 128-
    # aligned (Mosaic lane rule) — _pick_group guarantees G·hd % 128 == 0;
    # per-head writes inside the span assemble in registers first.
    bi, g = pl.program_id(0), pl.program_id(1)
    F = nh * hd
    dqs, dks, dvs = [], [], []
    for j in range(G):
        q = _head(q_ref, j, hd)
        k = _head(k_ref, j, hd)
        v = _head(v_ref, j, hd)
        do = _head(do_ref, j, hd)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask_2d(s)
        if per_row_lens:
            s = _kv_mask_2d(s, lens_ref[bi, 0])
        elif kv_len is not None:
            s = _kv_mask_2d(s, kv_len)
        sigma = _softmax_f32(s)
        dpd = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        if drop_p > 0.0:
            m = _drop_mask(seed_ref, bi, g * G + j, nh, s.shape, drop_p)
            pd = sigma * m           # dropped probabilities (fwd replay)
            dsig = dpd * m           # grad through the same mask
        else:
            pd = sigma
            dsig = dpd
        dvs.append(jnp.dot(pd.astype(do.dtype).T, do,
                           preferred_element_type=jnp.float32))
        # softmax vjp with the row term computed exactly in-register
        r = jnp.sum(dsig * sigma, axis=-1, keepdims=True)
        ds = (sigma * (dsig - r)).astype(q.dtype)
        dqs.append(jnp.dot(ds, k, preferred_element_type=jnp.float32)
                   * scale)
        dks.append(jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
                   * scale)
    span = G * hd
    base = g * span
    dt = dqkv_ref.dtype
    dqkv_ref[0, :, pl.ds(base, span)] = \
        jnp.concatenate(dqs, axis=-1).astype(dt)
    dqkv_ref[0, :, pl.ds(F + base, span)] = \
        jnp.concatenate(dks, axis=-1).astype(dt)
    dqkv_ref[0, :, pl.ds(2 * F + base, span)] = \
        jnp.concatenate(dvs, axis=-1).astype(dt)


def _pick_group(nh, hd, s, itemsize, n_bufs, fixed_bytes=0, batch=None):
    """Largest G dividing nh whose blocks fit the VMEM plan.

    n_bufs: resident (S, G·hd) stream buffers — inputs are double-buffered
    by the pipeline (count 2×), plus ~4 f32 (S,S) ephemerals for the
    score/prob/grad matrices. fixed_bytes: group-size-independent residents
    (the backward's full (S, 3F) dqkv output block, double-buffered)."""
    eph = 4 * s * s * 4 + fixed_bytes
    aligned = [G for G in range(nh, 0, -1)
               if nh % G == 0 and (G * hd) % 128 == 0]
    if not aligned:
        raise ValueError(
            f"fused_mha: no head group of nh={nh} hd={hd} satisfies the "
            f"128-lane alignment rule (use_fused_mha should have gated)")
    best = aligned[-1]   # smallest aligned group as the floor
    for G in aligned:
        blocks = n_bufs * 2 * s * G * hd * itemsize
        if blocks + eph <= _VMEM_BUDGET:
            best = G
            break
    # measured on v5e (S=197 nh=16 hd=64): at B=64 G=8 beats G=16 (two
    # groups per batch item pipeline DMA against compute, full-step 66.2%
    # vs lower); at B=32 the FULL STEP prefers G=16 (56.3% vs 54.2% at
    # G=8 — fewer, fatter programs when the grid is short). The r4 note
    # preferring G=8 universally came from a forward-only microbench.
    while best > 8 and nh % (best // 2) == 0 and (batch is None
                                                  or batch > 32):
        best //= 2
    return best


def _i0():
    # index-map literal must be i32 — a bare python 0 traces as i64 under
    # x64, which Mosaic refuses (same workaround as flash_attention.py)
    return jnp.int32(0)


def _smem_spec():
    # explicit i32 index map: the default map emits python-int literals,
    # which trace as i64 under x64 and Mosaic refuses to return
    return pl.BlockSpec((1, 1), lambda bi, g: (_i0(), _i0()),
                        memory_space=pltpu.SMEM)


def _specs(G, hd, s, n_groups):
    """One (1, S, G·hd) block per (batch, group) over a packed [B,S,F]
    array; q/k/v additionally offset by their third of a fused [B,S,3F]."""
    def at(third):
        return pl.BlockSpec(
            (1, s, G * hd),
            lambda bi, g, _t=third: (bi, _i0(), _t * n_groups + g))
    return at


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9, 10))
def _mha(qkv, seed, lensf, nh, scale, kv_len, causal, drop_p, G, interpret,
         use_lens):
    return _mha_fwd(qkv, seed, lensf, nh, scale, kv_len, causal, drop_p, G,
                    interpret, use_lens)


def _lens_spec(b):
    # full [B,1] i32 table in SMEM; every program reads its own row
    return pl.BlockSpec((b, 1), lambda bi, g: (_i0(), _i0()),
                        memory_space=pltpu.SMEM)


def _mha_fwd(qkv, seed, lensf, nh, scale, kv_len, causal, drop_p, G,
             interpret, use_lens):
    b, s, F3 = qkv.shape
    F = F3 // 3
    hd = F // nh
    n_groups = nh // G
    at = _specs(G, hd, s, n_groups)
    extra_specs = [_lens_spec(b)] if use_lens else []
    extra_args = [lensf.astype(jnp.int32)] if use_lens else []
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, nh=nh, hd=hd, G=G, scale=scale,
                          kv_len=kv_len, causal=causal, drop_p=drop_p,
                          per_row_lens=use_lens),
        out_shape=jax.ShapeDtypeStruct((b, s, F), qkv.dtype),
        grid=(b, n_groups),
        in_specs=[
            _smem_spec(), *extra_specs,
            at(0), at(1), at(2),
        ],
        out_specs=pl.BlockSpec((1, s, G * hd), lambda bi, g: (bi, _i0(), g)),
        interpret=interpret,
    )(jax.lax.bitcast_convert_type(seed, jnp.int32),
      *extra_args, qkv, qkv, qkv)
    return out


def _mha_vjp_fwd(qkv, seed, lensf, nh, scale, kv_len, causal, drop_p, G,
                 interpret, use_lens):
    out = _mha_fwd(qkv, seed, lensf, nh, scale, kv_len, causal, drop_p, G,
                   interpret, use_lens)
    return out, (qkv, seed, lensf)


def _mha_vjp_bwd(nh, scale, kv_len, causal, drop_p, G, interpret, use_lens,
                 res, g_out):
    qkv, seed, lensf = res
    b, s, F3 = qkv.shape
    F = F3 // 3
    hd = F // nh
    # the backward streams 4 group-sized buffers (q,k,v,do in) plus the
    # FULL (S, 3F) dqkv output block, which is group-size-independent and
    # double-buffered across the batch grid dim — budget it as fixed
    # note: no batch= here — the measured B=32 configuration (ViT-L 56.3%)
    # is fwd G=16 / bwd G=8: the backward's resident dqkv block already
    # fattens its programs, so the small-batch large-G preference is a
    # forward-only effect
    Gb = min(G, _pick_group(nh, hd, s, qkv.dtype.itemsize, n_bufs=4,
                            fixed_bytes=2 * s * F3 * qkv.dtype.itemsize))
    while Gb > 1 and (nh % Gb or (Gb * hd) % 128):
        Gb -= 1
    n_groups = nh // Gb
    at = _specs(Gb, hd, s, n_groups)
    gspec = pl.BlockSpec((1, s, Gb * hd), lambda bi, gg: (bi, _i0(), gg))
    extra_specs = [_lens_spec(b)] if use_lens else []
    extra_args = [lensf.astype(jnp.int32)] if use_lens else []
    dqkv = pl.pallas_call(
        functools.partial(_bwd_kernel, nh=nh, hd=hd, G=Gb, scale=scale,
                          kv_len=kv_len, causal=causal, drop_p=drop_p,
                          per_row_lens=use_lens),
        out_shape=jax.ShapeDtypeStruct((b, s, F3), qkv.dtype),
        grid=(b, n_groups),
        in_specs=[
            _smem_spec(), *extra_specs,
            at(0), at(1), at(2), gspec,
        ],
        out_specs=pl.BlockSpec((1, s, F3),
                               lambda bi, gg: (bi, _i0(), _i0())),
        interpret=interpret,
    )(jax.lax.bitcast_convert_type(seed, jnp.int32),
      *extra_args, qkv, qkv, qkv, g_out)
    return dqkv, jnp.zeros_like(seed), jnp.zeros_like(lensf)


_mha.defvjp(_mha_vjp_fwd, _mha_vjp_bwd)


def mha_reference_packed(qkv, num_heads, *, scale=None, kv_len=None,
                         causal=False, score_dtype=None):
    """XLA fallback with identical signature (no dropout): unpack, run the
    shared reference softmax-attention, repack."""
    from ..attention import attention_reference
    b, s, F3 = qkv.shape
    F = F3 // 3
    hd = F // num_heads
    a = qkv.reshape(b, s, 3, num_heads, hd)
    mask = None
    if kv_len is not None and kv_len < s:
        mask = (jnp.arange(s) < kv_len)[None, None, None, :]
    out = attention_reference(a[:, :, 0], a[:, :, 1], a[:, :, 2], mask=mask,
                              is_causal=causal, scale=scale,
                              score_dtype=score_dtype)
    return out.reshape(b, s, F)


def use_fused_mha(s, num_heads, head_dim, max_seq=768):
    # max_seq: the per-head (S,S) f32 score/prob ephemerals must fit
    # scoped VMEM alongside the stream buffers — 768 is the measured
    # ceiling class on 16M chips; longer sequences belong to the
    # streaming flash kernel anyway
    """Gate: TPU-class platform, lane-sliceable heads, short sequence."""
    import os
    force = os.environ.get("PADDLE_TPU_FUSED_MHA")
    if force == "0":
        return False
    if force != "1":
        try:
            d = jax.devices()[0].platform
        except RuntimeError:
            return False
        if d not in ("tpu", "axon"):
            return False
    return (head_dim % 8 == 0 and head_dim * num_heads % 128 == 0
            and s <= max_seq)


def fused_mha(qkv, num_heads, *, scale=None, kv_len=None, causal=False,
              dropout_p=0.0, dropout_seed=None, heads_per_program=None,
              interpret: bool = False):
    """Fused short-sequence attention on the packed projection output.

    qkv: [B, S, 3·nh·hd] laid out [q heads | k heads | v heads] (the
        reshape-[B,S,3,nh,hd] convention of every encoder block here).
    kv_len: static count of valid key rows (padding mask).
    dropout_p: attention-PROBABILITY dropout rate; needs dropout_seed — a
        float32 scalar (traced ok) whose int32 cast seeds the Mosaic PRNG.
    Returns [B, S, nh·hd] context in the same packed layout.

    S is padded to the 128-lane boundary internally (scores' last dim must
    tile); padded keys are masked via kv_len, padded query rows are sliced
    off and contribute zero gradient.
    """
    b, s, F3 = qkv.shape
    F = F3 // 3
    hd = F // num_heads
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("fused_mha: dropout_p > 0 requires dropout_seed")
    lens_arr = None
    if kv_len is not None and not isinstance(kv_len, int):
        # per-batch-row valid lengths (right-padded batches) — [B] ints
        lens_arr = jnp.asarray(kv_len, jnp.float32).reshape(b, 1)
        kv_len = None
    if kv_len is not None and kv_len <= 0:
        raise ValueError(f"fused_mha: kv_len must be positive, got {kv_len}")
    # No sequence padding: Mosaic masks unaligned block dims natively
    # (measured exact at S=197 on v5e), so ragged lengths cost nothing —
    # the r4 padded variant spent ~1 ms/layer on pad/slice/concat copies.
    if kv_len is not None and kv_len >= s:
        kv_len = None
    if dropout_p > 0.0:
        # float32 carrier for the PRNG seed: custom_vjp requires float
        # primals (int args have no cotangent type). The int seed is packed
        # LOSSLESSLY by bitcast (a value-cast to f32 would round seeds
        # >= 2^24 to multiples of up to 128, shrinking the seed space);
        # the kernel bitcasts back to int32 before SMEM.
        seed = jax.lax.bitcast_convert_type(
            jnp.asarray(dropout_seed).astype(jnp.int32),
            jnp.float32).reshape(1, 1)
    else:
        seed = jnp.zeros((1, 1), jnp.float32)
    if heads_per_program is None:
        # env override rides through the SAME validation as explicit args
        import os
        heads_per_program = (
            int(os.environ.get("PADDLE_TPU_FUSED_MHA_G", "0")) or None)
    if heads_per_program is not None and (
            num_heads % heads_per_program
            or (heads_per_program * hd) % 128):
        # validated HERE so the backward's group-shrink loop can never
        # silently land on an unaligned dqkv span offset (Mosaic lane rule)
        raise ValueError(
            f"fused_mha: heads_per_program={heads_per_program} must divide "
            f"num_heads={num_heads} with heads_per_program*head_dim "
            f"({heads_per_program * hd}) a multiple of 128")
    G = heads_per_program or _pick_group(num_heads, hd, s, qkv.dtype.itemsize,
                                         n_bufs=4, batch=b)
    use_lens = lens_arr is not None
    if lens_arr is None:
        lens_arr = jnp.zeros((b, 1), jnp.float32)   # float carrier (vjp)
    return _mha(qkv, seed, lens_arr, int(num_heads), float(scale),
                None if kv_len is None else int(kv_len), bool(causal),
                float(dropout_p), int(G), bool(interpret), bool(use_lens))
