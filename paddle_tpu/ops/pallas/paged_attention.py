"""Pallas TPU kernels: decode and ragged multi-token attention over a
paged KV pool.

The serving decode problem (ISSUE 5; Ragged Paged Attention, arxiv
2604.15464): each batch row's KV cache is a list of fixed-size blocks
scattered through one [num_blocks, block, H, D] pool, named by an int32
block table. The XLA-visible alternative — gather the blocks into a
contiguous [B, L, H, D] buffer, then attend — materializes the whole
working set in HBM twice per step (`paged_attention_reference`, the
CPU/tier-1 path). This kernel instead walks the block table directly:

  grid (B, MB)   one program per (batch row, table slot), MB innermost so
                 the online-softmax state lives in VMEM scratch across a
                 row's blocks (same accumulator pattern as
                 flash_attention.py);
  block fetch    the K/V BlockSpec index maps read the SCALAR-PREFETCHED
                 block table — Pallas DMAs exactly the pool page the row
                 needs next, so HBM traffic is the true KV bytes, not the
                 padded envelope. Table padding entries are 0 (the trash
                 block), and consecutive same-index fetches collapse in
                 the pipeline, so invalid tail slots cost ~nothing;
  masking        global column j*bs + i is attendable iff < lens[row];
                 blocks entirely past lens skip their accumulate
                 (`pl.when`), partial blocks mask per column.

Compute is deliberately VPU-only (broadcast-multiply-reduce per head, the
q vector is 1 token — there is no MXU shape here worth a relayout); decode
attention is KV-bandwidth-bound, so the fetch pattern IS the optimization.
Numerics: f32 scores/softmax/accumulation whatever the pool dtype (like
the other Pallas kernels here — the XLA static-cache path instead stores
scores in the model dtype, so bf16 models' kernel-vs-reference parity is
approximate; see tools/validate_paged_tpu.py).

Rows with lens == 0 (dummy batch slots) output zeros (the reference path
outputs masked-uniform garbage instead — both are dropped by callers, and
the parity tests compare live rows).

CPU validation runs this kernel in interpret mode (tests); on-chip
compiled parity is tools/validate_paged_tpu.py, same split as the other
Pallas kernels here.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG = -1e30


def _kernel(tables_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
            m_sc, l_sc, acc_sc, *, scale, nh, bs, n_slots):
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    ln = lens_ref[b]

    @pl.when(j * bs < ln)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # [nh, hd]
        k = k_ref[0].astype(jnp.float32)            # [bs, nh, hd]
        v = v_ref[0].astype(jnp.float32)
        col = j * bs + lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        keep = col < ln
        # per-head online softmax on the VPU: q is one token, so the
        # "matmul" is a broadcast multiply + lane reduction; nh unrolls
        # statically (serving configs keep nh <= 40)
        for h in range(nh):
            s = jnp.sum(k[:, h, :] * q[h:h + 1, :], axis=-1,
                        keepdims=True) * scale      # [bs, 1]
            s = jnp.where(keep, s, jnp.asarray(_NEG, s.dtype))
            m_prev = m_sc[h:h + 1, :]               # [1, 1]
            l_prev = l_sc[h:h + 1, :]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
            p = jnp.exp(s - m_new)                  # [bs, 1]
            corr = jnp.exp(m_prev - m_new)
            m_sc[h:h + 1, :] = m_new
            l_sc[h:h + 1, :] = corr * l_prev + jnp.sum(p, axis=0,
                                                       keepdims=True)
            acc_sc[h:h + 1, :] = corr * acc_sc[h:h + 1, :] + jnp.sum(
                p * v[:, h, :], axis=0, keepdims=True)

    @pl.when(j == n_slots - 1)
    def _finish():
        l = jnp.maximum(l_sc[...], 1e-30)           # lens==0 rows -> zeros
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)


def _kernel_q8(tables_ref, lens_ref, q_ref, kc_ref, ks_ref, vc_ref,
               vs_ref, o_ref, m_sc, l_sc, acc_sc, *, scale, nh, bs,
               n_slots):
    """int8 paged decode attention (ISSUE 10): the pools carry int8
    codes + per-(row, head) f32 factored scales. Same online-softmax
    skeleton as `_kernel`; the static int8-KV trick applies per block —
    the scale is constant over head_dim, so it factors OUT of both
    contractions: codes stream as bare int8->f32 converts and the scale
    multiplies land on the [bs, 1] score / prob columns."""
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    ln = lens_ref[b]

    @pl.when(j * bs < ln)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # [nh, hd]
        kc = kc_ref[0].astype(jnp.float32)          # [bs, nh, hd] codes
        ks = ks_ref[0]                              # [bs, nh] f32 scales
        vc = vc_ref[0].astype(jnp.float32)
        vs = vs_ref[0]
        col = j * bs + lax.broadcasted_iota(jnp.int32, (bs, 1), 0)
        keep = col < ln
        for h in range(nh):
            s = jnp.sum(kc[:, h, :] * q[h:h + 1, :], axis=-1,
                        keepdims=True) * (ks[:, h:h + 1] * scale)
            s = jnp.where(keep, s, jnp.asarray(_NEG, s.dtype))
            m_prev = m_sc[h:h + 1, :]
            l_prev = l_sc[h:h + 1, :]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=0, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            m_sc[h:h + 1, :] = m_new
            l_sc[h:h + 1, :] = corr * l_prev + jnp.sum(p, axis=0,
                                                       keepdims=True)
            acc_sc[h:h + 1, :] = corr * acc_sc[h:h + 1, :] + jnp.sum(
                (p * vs[:, h:h + 1]) * vc[:, h, :], axis=0, keepdims=True)

    @pl.when(j == n_slots - 1)
    def _finish():
        l = jnp.maximum(l_sc[...], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)


def paged_attention_q8_kernel(q, kc_pool, ks_pool, vc_pool, vs_pool,
                              tables, lens, *, scale=None,
                              interpret=False):
    """q [B, 1, H, D] (or [B, H, D]); code pools int8 [NB, bs, H, D];
    scale pools f32 [NB, bs, H]; tables [B, MB] i32; lens [B]. Returns
    the same layout/dtype as q."""
    squeezed = q.ndim == 4
    if squeezed:
        if q.shape[1] != 1:
            raise ValueError(f"paged decode kernel serves one token per "
                             f"row; got q seq len {q.shape[1]}")
        q3 = q[:, 0]
    else:
        q3 = q
    b, nh, hd = q3.shape
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    mb = tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    pool_spec = pl.BlockSpec((1, bs, nh, hd),
                             lambda bi, j, T, L: (T[bi, j], 0, 0, 0))
    scale_spec = pl.BlockSpec((1, bs, nh),
                              lambda bi, j, T, L: (T[bi, j], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda bi, j, T, L: (bi, 0, 0)),
            pool_spec, scale_spec, pool_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, nh, hd), lambda bi, j, T, L: (bi, 0, 0)),
        scratch_shapes=[pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, hd), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel_q8, scale=scale, nh=nh, bs=bs,
                          n_slots=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lens.astype(jnp.int32), q3,
      kc_pool, ks_pool, vc_pool, vs_pool)
    return out[:, None] if squeezed else out


# ------------------------------------------- ragged multi-token kernels
# ISSUE 11 (Ragged Paged Attention, arxiv 2604.15464): one kernel serving
# k >= 1 query tokens per row against that row's block-table KV with a
# per-row START offset — query row i of batch row b sits at global
# position start[b] + i and attends pool columns <= its own position
# (causal within the window, over the cached prefix + the window itself).
# This is the [B, k] primitive behind suffix prefill after a partial
# prefix hit, chunked prefill, and speculative-decode verification; k = 1
# with start = lens degenerates to the decode kernel above (parity
# pinned in tests). Unlike the 1-token kernel the per-block math here IS
# an MXU shape where k permits: scores are a [k, hd] x [hd, bs] dot and
# the value accumulate a [k, bs] x [bs, hd] dot, so wide windows (suffix
# prefill at k = prompt_cap, spec verify at k = spec window) run on the
# MXU while the fetch pattern stays the block-table walk.

def _kernel_multi(tables_ref, start_ref, q_ref, k_ref, v_ref, o_ref,
                  m_sc, l_sc, acc_sc, *, scale, nh, bs, s, n_slots):
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    st = start_ref[b]

    @pl.when(j * bs <= st + s - 1)       # block wholly past the window's
    def _step():                         # causal frontier: skip the fetch
        q = q_ref[0].astype(jnp.float32)            # [s, nh, hd]
        k = k_ref[0].astype(jnp.float32)            # [bs, nh, hd]
        v = v_ref[0].astype(jnp.float32)
        col = j * bs + lax.broadcasted_iota(jnp.int32, (s, bs), 1)
        row = lax.broadcasted_iota(jnp.int32, (s, bs), 0)
        keep = col <= st + row           # causal across prefix + window
        for h in range(nh):
            sc = lax.dot_general(q[:, h, :], k[:, h, :],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            sc = sc * scale                          # [s, bs]
            sc = jnp.where(keep, sc, jnp.asarray(_NEG, sc.dtype))
            m_prev = m_sc[h]                         # [s, 1]
            l_prev = l_sc[h]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
            p = jnp.exp(sc - m_new)                  # [s, bs]
            corr = jnp.exp(m_prev - m_new)
            m_sc[h] = m_new
            l_sc[h] = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_sc[h] = corr * acc_sc[h] + lax.dot_general(
                p, v[:, h, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # [s, hd]

    @pl.when(j == n_slots - 1)
    def _finish():
        for h in range(nh):
            l = jnp.maximum(l_sc[h], 1e-30)
            o_ref[0, :, h, :] = (acc_sc[h] / l).astype(o_ref.dtype)


def _kernel_multi_q8(tables_ref, start_ref, q_ref, kc_ref, ks_ref, vc_ref,
                     vs_ref, o_ref, m_sc, l_sc, acc_sc, *, scale, nh, bs,
                     s, n_slots):
    """int8 form of `_kernel_multi`: codes stream as bare int8->f32
    converts into the dots; the per-(row, head) factored scales multiply
    the [s, bs] score / probability tiles (same trick as `_kernel_q8`,
    MXU-shaped like `_kernel_multi`)."""
    b, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    st = start_ref[b]

    @pl.when(j * bs <= st + s - 1)
    def _step():
        q = q_ref[0].astype(jnp.float32)            # [s, nh, hd]
        kc = kc_ref[0].astype(jnp.float32)          # [bs, nh, hd] codes
        ks = ks_ref[0]                              # [bs, nh] f32 scales
        vc = vc_ref[0].astype(jnp.float32)
        vs = vs_ref[0]
        col = j * bs + lax.broadcasted_iota(jnp.int32, (s, bs), 1)
        row = lax.broadcasted_iota(jnp.int32, (s, bs), 0)
        keep = col <= st + row
        for h in range(nh):
            sc = lax.dot_general(q[:, h, :], kc[:, h, :],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            sc = sc * (ks[:, h][None, :] * scale)    # [s, bs]
            sc = jnp.where(keep, sc, jnp.asarray(_NEG, sc.dtype))
            m_prev = m_sc[h]
            l_prev = l_sc[h]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
            p = jnp.exp(sc - m_new)
            corr = jnp.exp(m_prev - m_new)
            m_sc[h] = m_new
            l_sc[h] = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
            acc_sc[h] = corr * acc_sc[h] + lax.dot_general(
                p * vs[:, h][None, :], vc[:, h, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    @pl.when(j == n_slots - 1)
    def _finish():
        for h in range(nh):
            l = jnp.maximum(l_sc[h], 1e-30)
            o_ref[0, :, h, :] = (acc_sc[h] / l).astype(o_ref.dtype)


def paged_prefix_attention_kernel(q, k_pool, v_pool, tables, start, *,
                                  scale=None, interpret=False):
    """Ragged multi-token paged attention: q [B, S, H, D] query tokens at
    global positions start[b] + i; pools [NB, bs, H, D]; tables [B, MB]
    i32; start [B] i32. Each query row attends every pool column <= its
    own position — the kernel form of `paged_prefix_attention_reference`
    (suffix prefill, chunked prefill, spec-decode verify; S = 1 with
    start = lens is exactly the decode case). Returns q's layout."""
    b, s, nh, hd = q.shape
    nb, bs = k_pool.shape[0], k_pool.shape[1]
    mb = tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    pool_spec = pl.BlockSpec((1, bs, nh, hd),
                             lambda bi, j, T, S_: (T[bi, j], 0, 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, s, nh, hd), lambda bi, j, T, S_: (bi, 0, 0, 0)),
            pool_spec, pool_spec,
        ],
        out_specs=pl.BlockSpec((1, s, nh, hd),
                               lambda bi, j, T, S_: (bi, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((nh, s, 1), jnp.float32),
                        pltpu.VMEM((nh, s, 1), jnp.float32),
                        pltpu.VMEM((nh, s, hd), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_multi, scale=scale, nh=nh, bs=bs, s=s,
                          n_slots=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, nh, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), start.astype(jnp.int32), q, k_pool, v_pool)


def paged_prefix_attention_q8_kernel(q, kc_pool, ks_pool, vc_pool, vs_pool,
                                     tables, start, *, scale=None,
                                     interpret=False):
    """int8 ragged multi-token paged attention: the q8 pools form of
    `paged_prefix_attention_kernel` (codes int8 [NB, bs, H, D], factored
    scales f32 [NB, bs, H])."""
    b, s, nh, hd = q.shape
    nb, bs = kc_pool.shape[0], kc_pool.shape[1]
    mb = tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    pool_spec = pl.BlockSpec((1, bs, nh, hd),
                             lambda bi, j, T, S_: (T[bi, j], 0, 0, 0))
    scale_spec = pl.BlockSpec((1, bs, nh),
                              lambda bi, j, T, S_: (T[bi, j], 0, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, s, nh, hd), lambda bi, j, T, S_: (bi, 0, 0, 0)),
            pool_spec, scale_spec, pool_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, s, nh, hd),
                               lambda bi, j, T, S_: (bi, 0, 0, 0)),
        scratch_shapes=[pltpu.VMEM((nh, s, 1), jnp.float32),
                        pltpu.VMEM((nh, s, 1), jnp.float32),
                        pltpu.VMEM((nh, s, hd), jnp.float32)],
    )
    return pl.pallas_call(
        functools.partial(_kernel_multi_q8, scale=scale, nh=nh, bs=bs, s=s,
                          n_slots=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, s, nh, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), start.astype(jnp.int32), q,
      kc_pool, ks_pool, vc_pool, vs_pool)


def paged_attention_kernel(q, k_pool, v_pool, tables, lens, *, scale=None,
                           interpret=False):
    """q [B, 1, H, D] (or [B, H, D]); pools [NB, bs, H, D]; tables
    [B, MB] i32; lens [B] = attendable rows per batch entry. Returns the
    same layout as q."""
    squeezed = q.ndim == 4
    if squeezed:
        if q.shape[1] != 1:
            raise ValueError(f"paged decode kernel serves one token per "
                             f"row; got q seq len {q.shape[1]}")
        q3 = q[:, 0]
    else:
        q3 = q
    b, nh, hd = q3.shape
    nb, bs = k_pool.shape[0], k_pool.shape[1]
    mb = tables.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda bi, j, T, L: (bi, 0, 0)),
            pl.BlockSpec((1, bs, nh, hd),
                         lambda bi, j, T, L: (T[bi, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, nh, hd),
                         lambda bi, j, T, L: (T[bi, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd), lambda bi, j, T, L: (bi, 0, 0)),
        scratch_shapes=[pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, 1), jnp.float32),
                        pltpu.VMEM((nh, hd), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, nh=nh, bs=bs, n_slots=mb),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nh, hd), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), lens.astype(jnp.int32), q3, k_pool, v_pool)
    return out[:, None] if squeezed else out
