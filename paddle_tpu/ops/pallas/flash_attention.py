"""Pallas flash attention for TPU.

Beyond-reference capability (SURVEY §5.7: the reference snapshot has no flash
attention — its fused_attention_op.cu materializes the full S×S probability
matrix). This kernel computes attention blockwise with an online softmax so
HBM traffic is O(S·D) instead of O(S²): Q tiles stay resident in VMEM, K/V
stream through in BK-sized blocks, and the MXU sees [BQ,D]x[D,BK] matmuls.

Layout: [batch, seq, heads, head_dim] in, same out (paddle convention).
Forward is the Pallas kernel; backward currently recomputes through the XLA
reference path under jax.custom_vjp (correct, O(S²) peak in backward —
a blockwise backward kernel is the planned upgrade).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BQ = 256
DEFAULT_BK = 256


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, bk):
    """One (batch*head, q_block) program: online-softmax over K/V blocks."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale           # [BQ, D]
    bq = q.shape[0]
    s_k = k_ref.shape[1]
    n_kb = s_k // bk

    m0 = jnp.full((bq, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    if causal:
        # only blocks whose start is <= last query index of this tile
        upper = lax.div((qi + 1) * bq + bk - 1, bk)
        upper = jnp.minimum(upper, n_kb)
    else:
        upper = n_kb

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * bk, bk), :].astype(jnp.float32)   # [BK, D]
        v = v_ref[0, pl.ds(ki * bk, bk), :].astype(jnp.float32)   # [BK, D]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)   # [BQ, BK]
        if causal:
            q_idx = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_idx >= k_idx, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.dot(p, v, preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, scale, causal, bq, bk, interpret):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    # fold heads into batch; seq-major for contiguous K/V streaming
    qt = jnp.moveaxis(q, 2, 1).reshape(b * h, s_q, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * h, s_k, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * h, s_k, d)

    grid = (b * h, s_q // bq)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bk=bk),
        out_shape=jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
        interpret=interpret,
    )(qt, kt, vt)
    return jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)


def _reference(q, k, v, *, scale, causal):
    from ..attention import attention_reference
    return attention_reference(q, k, v, is_causal=causal, scale=scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, bq, bk, interpret):
    return _flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk,
                      interpret=interpret)


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk, interpret):
    out = _flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk,
                     interpret=interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(scale, causal, bq, bk, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _reference(a, b, c, scale=scale, causal=causal),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = False):
    """Differentiable flash attention on [B, S, H, D] arrays."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k = q.shape[1], k.shape[1]
    bq = block_q or min(DEFAULT_BQ, s_q)
    bk = block_k or min(DEFAULT_BK, s_k)
    while s_q % bq:
        bq //= 2
    while s_k % bk:
        bk //= 2
    if bq < 8 or bk < 8:
        return _reference(q, k, v, scale=scale, causal=causal)
    return _flash(q, k, v, float(scale), bool(causal), int(bq), int(bk), bool(interpret))
