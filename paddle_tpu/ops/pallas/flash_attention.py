"""Pallas flash attention for TPU — streaming forward AND blockwise backward.

Beyond-reference capability (SURVEY §5.7: the reference snapshot has no flash
attention — its fused_attention_op.cu materializes the full S×S probability
matrix). Both passes compute attention blockwise with an online/stored
softmax so HBM traffic is O(S·D) instead of O(S²).

Kernel shape: 3-D sequential grids — (batch·head, q_block, k_block) for the
forward and dQ, (batch·head, k_block, q_block) for dK/dV — with the running
accumulators (m, l, acc / dq / dk,dv) living in VMEM scratch that persists
across the innermost grid dimension. Only one (bq,d) + one (bk,d) tile is
resident per step, so sequence length is bounded by HBM, not VMEM (the
previous full-K/V-block design hit the 16M scoped-vmem limit at S=16k).

Backward follows FlashAttention-2: forward stores per-row logsumexp L
(replicated over 8 sublanes — TPU blocks tile (8,128)); backward recomputes
P = exp(QKᵀ·scale − L) tile by tile with Δ = rowsum(dO ⊙ O) precomputed.

Layout: [batch, seq, heads, head_dim] in, same out (paddle convention).
head_dim pads to the 128-lane boundary in the wrapper (zero pads change no
dot product), so 64-dim heads work. Matmuls run on bf16 inputs with f32
accumulation (preferred_element_type) — full MXU rate.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BQ = 1024
DEFAULT_BK = 1024
_NEG = -1e30


def _i0():
    # index-map literal: must be i32 — with x64 enabled a bare python 0
    # traces as i64, which Mosaic refuses to return from the index fn
    return jnp.int32(0)


def _causal_mask(s, qi, ki, bq, bk):
    q_idx = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_idx >= k_idx, s, jnp.asarray(_NEG, s.dtype))


def _kv_mask(s, ki, bk, kv_len):
    """Mask key columns with global index >= kv_len (static padding mask).

    Lets callers with ragged/odd sequence lengths (e.g. ViT's 197 tokens)
    zero-pad K/V up to the 128-row block boundary: padded columns score
    -inf, so exp() gives them zero probability and zero dk/dv."""
    k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(k_idx < kv_len, s, jnp.asarray(_NEG, s.dtype))


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc,
                *, scale, causal, n_kb, kv_len=None):
    qi, ki = pl.program_id(1), pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # causal: blocks fully above the diagonal contribute nothing
    needed = True if not causal else (ki * bk <= (qi + 1) * bq - 1)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        if kv_len is not None:
            s = _kv_mask(s, ki, bk, kv_len)
        m_prev, l_prev = m_sc[...], l_sc[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        m_sc[...] = m_new
        l_sc[...] = corr * l_prev + p.sum(axis=-1, keepdims=True)
        acc_sc[...] = corr * acc_sc[...] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        l = jnp.maximum(l_sc[...], 1e-30)
        o_ref[0] = (acc_sc[...] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to((m_sc[...] + jnp.log(l))[:, 0][None, :],
                                      (8, bq))


def _flash_fwd(q, k, v, *, scale, causal, bq, bk, interpret, kv_len=None):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    qt = jnp.moveaxis(q, 2, 1).reshape(b * h, s_q, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * h, s_k, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * h, s_k, d)
    n_kb = s_k // bk

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, n_kb=n_kb,
                          kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, 8, s_q), jnp.float32)),
        grid=(b * h, s_q // bq, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, _i0())),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, _i0())),
            pl.BlockSpec((1, bk, d), lambda bh, qi, ki: (bh, ki, _i0())),
        ],
        out_specs=(pl.BlockSpec((1, bq, d), lambda bh, qi, ki: (bh, qi, _i0())),
                   pl.BlockSpec((1, 8, bq), lambda bh, qi, ki: (bh, _i0(), qi))),
        scratch_shapes=[pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, 1), jnp.float32),
                        pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse, (qt, kt, vt)


# ----------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_sc, *, scale, causal, n_kb, kv_len=None):
    qi, ki = pl.program_id(1), pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    needed = True if not causal else (ki * bk <= (qi + 1) * bq - 1)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        if kv_len is not None:
            s = _kv_mask(s, ki, bk, kv_len)
        p = jnp.exp(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_sc[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal, n_qb,
                    kv_len=None):
    ki, qi = pl.program_id(1), pl.program_id(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    needed = True if not causal else ((qi + 1) * bq - 1 >= ki * bk)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        if kv_len is not None:
            s = _kv_mask(s, ki, bk, kv_len)
        p = jnp.exp(s - lse)
        pt = p.astype(do.dtype)
        dv_sc[...] += jnp.dot(pt.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_sc[...] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, bq, bk, interpret, kv_len=None):
    qt, kt, vt, out, lse = res
    bh, s_q, d = qt.shape
    s_k = kt.shape[1]
    dot = jnp.moveaxis(g, 2, 1).reshape(bh, s_q, d)
    delta = jnp.sum(dot.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, s_q))
    n_kb = s_k // bk
    n_qb = s_q // bq

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          n_kb=n_kb, kv_len=kv_len),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), qt.dtype),
        grid=(bh, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, _i0())),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, _i0())),
            pl.BlockSpec((1, bk, d), lambda b, qi, ki: (b, ki, _i0())),
            pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, _i0())),
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, _i0(), qi)),
            pl.BlockSpec((1, 8, bq), lambda b, qi, ki: (b, _i0(), qi)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, qi, ki: (b, qi, _i0())),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          n_qb=n_qb, kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((bh, s_k, d), kt.dtype),
                   jax.ShapeDtypeStruct((bh, s_k, d), vt.dtype)),
        grid=(bh, n_kb, n_qb),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, ki, qi: (b, qi, _i0())),
            pl.BlockSpec((1, bk, d), lambda b, ki, qi: (b, ki, _i0())),
            pl.BlockSpec((1, bk, d), lambda b, ki, qi: (b, ki, _i0())),
            pl.BlockSpec((1, bq, d), lambda b, ki, qi: (b, qi, _i0())),
            pl.BlockSpec((1, 8, bq), lambda b, ki, qi: (b, _i0(), qi)),
            pl.BlockSpec((1, 8, bq), lambda b, ki, qi: (b, _i0(), qi)),
        ],
        out_specs=(pl.BlockSpec((1, bk, d), lambda b, ki, qi: (b, ki, _i0())),
                   pl.BlockSpec((1, bk, d), lambda b, ki, qi: (b, ki, _i0()))),
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- custom_vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, scale, causal, bq, bk, interpret, kv_len=None,
           save_transposed=False):
    out, _, _ = _flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk,
                           interpret=interpret, kv_len=kv_len)
    b, s_q, h, d = q.shape
    return jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk, interpret, kv_len=None,
                   save_transposed=False):
    out, lse, (qt, kt, vt) = _flash_fwd(q, k, v, scale=scale, causal=causal,
                                        bq=bq, bk=bk, interpret=interpret,
                                        kv_len=kv_len)
    b, s_q, h, d = q.shape
    o = jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)
    if save_transposed:
        # residuals: the HEAD-MAJOR [b*h, s, d] copies the forward already
        # built — backward reuses them instead of re-transposing, saving 3
        # layout passes per layer (~20 ms/step on the 1.3B flagship at the
        # measured ~180 GB/s effective HBM bw) at +3·B·S·H·2B residual
        # memory. Right when HBM has headroom; wrong near the remat knee.
        return o, (qt, kt, vt, out, lse, (b, h))
    # default residuals: the ORIGINAL layouts (alias the layer's live
    # tensors) — the transposes are recomputed in bwd, saving 3 head-major
    # copies of q/k/v in HBM across the whole backward (~100MB at 1.3B
    # S=8192; the difference between fitting bf16 moments and OOM)
    return o, (q, k, v, out, lse, (b, h))


def _flash_vjp_bwd(scale, causal, bq, bk, interpret, kv_len, save_transposed,
                   res, g):
    q, k, v, out, lse, (b, h) = res
    d = q.shape[-1]
    if save_transposed:
        qt, kt, vt = q, k, v
    else:
        qt = jnp.moveaxis(q, 2, 1).reshape(b * h, q.shape[1], d)
        kt = jnp.moveaxis(k, 2, 1).reshape(b * h, k.shape[1], d)
        vt = jnp.moveaxis(v, 2, 1).reshape(b * h, v.shape[1], d)
    dq, dk, dv = _flash_bwd((qt, kt, vt, out, lse), g, scale=scale,
                            causal=causal, bq=bq, bk=bk, interpret=interpret,
                            kv_len=kv_len)
    s_q, s_k, d = dq.shape[1], dk.shape[1], dq.shape[2]
    dq = jnp.moveaxis(dq.reshape(b, h, s_q, d), 1, 2)
    dk = jnp.moveaxis(dk.reshape(b, h, s_k, d), 1, 2)
    dv = jnp.moveaxis(dv.reshape(b, h, s_k, d), 1, 2)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _reference(q, k, v, *, scale, causal):
    from ..attention import attention_reference
    return attention_reference(q, k, v, is_causal=causal, scale=scale)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = False, kv_len: int = None,
                    save_transposed: bool = None):
    """Differentiable flash attention on [B, S, H, D] arrays.

    kv_len: static number of VALID key/value rows; rows >= kv_len (zero
    padding up to the block boundary) receive -inf scores in forward and
    backward, so their probability and dk/dv are exactly zero.

    save_transposed: keep the forward's head-major q/k/v copies as
    backward residuals (saves 3 re-transpose passes per layer) at the cost
    of 3·B·S·H·2 bytes of residual memory. Default: env
    PADDLE_TPU_FLASH_SAVE_T ("1"/"0"), else False (memory-lean)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k = q.shape[1], k.shape[1]
    if kv_len is not None and kv_len <= 0:
        # every key column masked would make exp(s - m) == 1 uniformly and
        # return an average of V rather than erroring — reject up front
        raise ValueError(f"flash_attention: kv_len must be positive, "
                         f"got {kv_len}")
    if kv_len is not None and kv_len >= s_k:
        kv_len = None
    import os
    from . import autotune as _at0
    if block_q is None and block_k is None and _at0._OVERRIDE is not None:
        # in-context tuner (autotune.tune_in_step) forcing this candidate
        block_q, block_k = _at0._OVERRIDE
    env_bq = os.environ.get("PADDLE_TPU_FLASH_BQ")  # tuning knobs
    env_bk = os.environ.get("PADDLE_TPU_FLASH_BK")
    if block_q is None and block_k is None and not env_bq and not env_bk \
            and not interpret:
        from ...core import flags as _flags
        if _flags.get_flags("FLAGS_flash_autotune").get(
                "FLAGS_flash_autotune", False):
            # measured tile selection with a persistent cache (PHI
            # autotune analog; see autotune.py). Measurement only happens
            # on EAGER calls — under an outer jit the benchmark would be
            # staged into the caller's trace, so during tracing we consult
            # the cache and fall back to defaults on a miss.
            import jax.core as _core
            from . import autotune as _at
            sig = (q.shape[0], s_q, s_k, q.shape[2], q.shape[3],
                   int(causal), str(q.dtype))
            cached = _at.cached_blocks("flash_attention", sig)
            if cached is not None:
                block_q, block_k = cached
            elif not isinstance(q, _core.Tracer):
                block_q, block_k = _at.tune_flash_blocks(
                    q.shape[0], s_q, s_k, q.shape[2], q.shape[3], causal,
                    q.dtype)
    bq = block_q or int(env_bq) if (block_q or env_bq) else min(DEFAULT_BQ, s_q)
    bk = block_k or int(env_bk) if (block_k or env_bk) else min(DEFAULT_BK, s_k)
    bq = min(bq, s_q)
    bk = min(bk, s_k)
    while s_q % bq:
        bq //= 2
    while s_k % bk:
        bk //= 2
    if bq < 8 or bk < 8:
        if kv_len is not None:
            from ..attention import attention_reference
            kmask = (jnp.arange(s_k) < kv_len)[None, None, None, :]
            return attention_reference(q, k, v, mask=kmask, is_causal=causal,
                                       scale=scale)
        return _reference(q, k, v, scale=scale, causal=causal)
    d = q.shape[-1]
    pad = (-d) % 128
    if pad:
        cfg = [(0, 0)] * 3 + [(0, pad)]
        q = jnp.pad(q, cfg)
        k = jnp.pad(k, cfg)
        v = jnp.pad(v, cfg)
    if save_transposed is None:
        save_transposed = os.environ.get("PADDLE_TPU_FLASH_SAVE_T") == "1"
    out = _flash(q, k, v, float(scale), bool(causal), int(bq), int(bk),
                 bool(interpret), None if kv_len is None else int(kv_len),
                 bool(save_transposed))
    return out[..., :d] if pad else out


# ----------------------------------------------------- packed-layout kernel
# The [B, S, H, D] kernel above needs head-major [B*H, S, D] copies of
# q/k/v (and of dq/dk/dv/out on the way back) — ~11 layout passes per layer
# that cost ~85 ms/step on the GPT-1.3B flagship at the measured ~180 GB/s
# effective HBM bandwidth (r3 profile). This variant consumes the
# projection output DIRECTLY: q/k/v stay [B, S, H·D] (lane-contiguous),
# the grid is (B, q_block, k_block), and heads are a compile-time loop of
# 128-lane slices inside the kernel — zero transposes in fwd OR bwd.
# Requires head_dim == 128 (lane-tile-aligned slices): true for GPT-1.3B
# and GPT-6.7B (2048/16, 4096/32).

def _p_slice(ref0, h, hd):
    return ref0[:, h * hd:(h + 1) * hd]


def _packed_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc,
                       acc_sc, *, scale, causal, n_kb, nh, hd, kv_len=None):
    qi, ki = pl.program_id(1), pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, _NEG)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    needed = True if not causal else (ki * bk <= (qi + 1) * bq - 1)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        for h in range(nh):
            s = jnp.dot(_p_slice(q, h, hd), _p_slice(k, h, hd).T,
                        preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, ki, bq, bk)
            if kv_len is not None:
                s = _kv_mask(s, ki, bk, kv_len)
            m_prev = m_sc[:, h:h + 1]
            l_prev = l_sc[:, h:h + 1]
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            m_sc[:, h:h + 1] = m_new
            l_sc[:, h:h + 1] = corr * l_prev + p.sum(axis=-1, keepdims=True)
            acc_sc[:, h * hd:(h + 1) * hd] = (
                corr * acc_sc[:, h * hd:(h + 1) * hd]
                + jnp.dot(p.astype(v.dtype), _p_slice(v, h, hd),
                          preferred_element_type=jnp.float32))

    @pl.when(ki == n_kb - 1)
    def _finish():
        l = jnp.maximum(l_sc[...], 1e-30)                    # (bq, nh)
        lhd = jnp.repeat(l, hd, axis=1)                      # (bq, nh*hd)
        o_ref[0] = (acc_sc[...] / lhd).astype(o_ref.dtype)
        lse = m_sc[...] + jnp.log(l)                         # (bq, nh)
        lse_ref[0] = jnp.broadcast_to(
            lse.T[:, None, :], (nh, 8, bq)).reshape(nh * 8, bq)


def _packed_flash_fwd(q, k, v, *, scale, causal, bq, bk, interpret, nh,
                      kv_len=None):
    b, s_q, H = q.shape
    s_k = k.shape[1]
    hd = H // nh
    n_kb = s_k // bk

    out, lse = pl.pallas_call(
        functools.partial(_packed_fwd_kernel, scale=scale, causal=causal,
                          n_kb=n_kb, nh=nh, hd=hd, kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((b, s_q, H), q.dtype),
                   jax.ShapeDtypeStruct((b, nh * 8, s_q), jnp.float32)),
        grid=(b, s_q // bq, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, H), lambda bi, qi, ki: (bi, qi, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, qi, ki: (bi, ki, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, qi, ki: (bi, ki, _i0())),
        ],
        out_specs=(
            pl.BlockSpec((1, bq, H), lambda bi, qi, ki: (bi, qi, _i0())),
            pl.BlockSpec((1, nh * 8, bq), lambda bi, qi, ki: (bi, _i0(), qi)),
        ),
        scratch_shapes=[pltpu.VMEM((bq, nh), jnp.float32),
                        pltpu.VMEM((bq, nh), jnp.float32),
                        pltpu.VMEM((bq, H), jnp.float32)],
        interpret=interpret,
    )(q, k, v)
    return out, lse


def _packed_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dq_ref, dq_sc, *, scale, causal, n_kb, nh, hd,
                          kv_len=None):
    qi, ki = pl.program_id(1), pl.program_id(2)
    bq = q_ref.shape[1]
    bk = k_ref.shape[1]

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    needed = True if not causal else (ki * bk <= (qi + 1) * bq - 1)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse_all = lse_ref[0].reshape(nh, 8, bq)
        delta_all = delta_ref[0].reshape(nh, 8, bq)
        for h in range(nh):
            s = jnp.dot(_p_slice(q, h, hd), _p_slice(k, h, hd).T,
                        preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, ki, bq, bk)
            if kv_len is not None:
                s = _kv_mask(s, ki, bk, kv_len)
            lse = lse_all[h, 0][:, None]
            delta = delta_all[h, 0][:, None]
            p = jnp.exp(s - lse)
            dp = jnp.dot(_p_slice(do, h, hd), _p_slice(v, h, hd).T,
                         preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(k.dtype)
            dq_sc[:, h * hd:(h + 1) * hd] += jnp.dot(
                ds, _p_slice(k, h, hd), preferred_element_type=jnp.float32)

    @pl.when(ki == n_kb - 1)
    def _finish():
        dq_ref[0] = (dq_sc[...] * scale).astype(dq_ref.dtype)


def _packed_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                           dk_ref, dv_ref, dk_sc, dv_sc, *, scale, causal,
                           n_qb, nh, hd, kv_len=None):
    ki, qi = pl.program_id(1), pl.program_id(2)
    bk = k_ref.shape[1]
    bq = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    needed = True if not causal else ((qi + 1) * bq - 1 >= ki * bk)

    @pl.when(needed)
    def _step():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse_all = lse_ref[0].reshape(nh, 8, bq)
        delta_all = delta_ref[0].reshape(nh, 8, bq)
        for h in range(nh):
            s = jnp.dot(_p_slice(q, h, hd), _p_slice(k, h, hd).T,
                        preferred_element_type=jnp.float32) * scale
            if causal:
                s = _causal_mask(s, qi, ki, bq, bk)
            if kv_len is not None:
                s = _kv_mask(s, ki, bk, kv_len)
            lse = lse_all[h, 0][:, None]
            delta = delta_all[h, 0][:, None]
            p = jnp.exp(s - lse)
            pt = p.astype(do.dtype)
            dv_sc[:, h * hd:(h + 1) * hd] += jnp.dot(
                pt.T, _p_slice(do, h, hd),
                preferred_element_type=jnp.float32)
            dp = jnp.dot(_p_slice(do, h, hd), _p_slice(v, h, hd).T,
                         preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(q.dtype)
            dk_sc[:, h * hd:(h + 1) * hd] += jnp.dot(
                ds.T, _p_slice(q, h, hd),
                preferred_element_type=jnp.float32)

    @pl.when(qi == n_qb - 1)
    def _finish():
        dk_ref[0] = (dk_sc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[...].astype(dv_ref.dtype)


def _packed_flash_bwd(q, k, v, out, lse, g, *, scale, causal, bq, bk,
                      interpret, nh, kv_len=None):
    b, s_q, H = q.shape
    s_k = k.shape[1]
    hd = H // nh
    # backward kernels hold 2x f32 accumulator panels (bk, H) — clamp their
    # blocks to fit the 16M scoped-VMEM budget independently of the
    # forward's (the fwd carries only ONE panel and can afford 512);
    # re-establish divisibility after the clamp or the grid under-covers
    # the sequence and uncovered gradient rows come back as garbage
    bq = min(bq, 256)
    bk = min(bk, 256)
    while s_q % bq:
        bq //= 2
    while s_k % bk:
        bk //= 2
    n_kb = s_k // bk
    n_qb = s_q // bq
    # delta = rowsum(dO . O) per head: [B, S, nh] -> [B, nh*8, S]
    delta = jnp.sum((g.astype(jnp.float32) * out.astype(jnp.float32))
                    .reshape(b, s_q, nh, hd), axis=-1)       # [B, S, nh]
    delta = jnp.broadcast_to(jnp.moveaxis(delta, 1, 2)[:, :, None, :],
                             (b, nh, 8, s_q)).reshape(b, nh * 8, s_q)

    dq = pl.pallas_call(
        functools.partial(_packed_bwd_dq_kernel, scale=scale, causal=causal,
                          n_kb=n_kb, nh=nh, hd=hd, kv_len=kv_len),
        out_shape=jax.ShapeDtypeStruct((b, s_q, H), q.dtype),
        grid=(b, n_qb, n_kb),
        in_specs=[
            pl.BlockSpec((1, bq, H), lambda bi, qi, ki: (bi, qi, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, qi, ki: (bi, ki, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, qi, ki: (bi, ki, _i0())),
            pl.BlockSpec((1, bq, H), lambda bi, qi, ki: (bi, qi, _i0())),
            pl.BlockSpec((1, nh * 8, bq), lambda bi, qi, ki: (bi, _i0(), qi)),
            pl.BlockSpec((1, nh * 8, bq), lambda bi, qi, ki: (bi, _i0(), qi)),
        ],
        out_specs=pl.BlockSpec((1, bq, H), lambda bi, qi, ki: (bi, qi, _i0())),
        scratch_shapes=[pltpu.VMEM((bq, H), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_packed_bwd_dkv_kernel, scale=scale, causal=causal,
                          n_qb=n_qb, nh=nh, hd=hd, kv_len=kv_len),
        out_shape=(jax.ShapeDtypeStruct((b, s_k, H), k.dtype),
                   jax.ShapeDtypeStruct((b, s_k, H), v.dtype)),
        grid=(b, n_kb, n_qb),
        in_specs=[
            pl.BlockSpec((1, bq, H), lambda bi, ki, qi: (bi, qi, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, ki, qi: (bi, ki, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, ki, qi: (bi, ki, _i0())),
            pl.BlockSpec((1, bq, H), lambda bi, ki, qi: (bi, qi, _i0())),
            pl.BlockSpec((1, nh * 8, bq), lambda bi, ki, qi: (bi, _i0(), qi)),
            pl.BlockSpec((1, nh * 8, bq), lambda bi, ki, qi: (bi, _i0(), qi)),
        ],
        out_specs=(
            pl.BlockSpec((1, bk, H), lambda bi, ki, qi: (bi, ki, _i0())),
            pl.BlockSpec((1, bk, H), lambda bi, ki, qi: (bi, ki, _i0())),
        ),
        scratch_shapes=[pltpu.VMEM((bk, H), jnp.float32),
                        pltpu.VMEM((bk, H), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _packed_flash(q, k, v, nh, scale, causal, bq, bk, interpret, kv_len=None):
    out, _ = _packed_flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq,
                               bk=bk, interpret=interpret, nh=nh,
                               kv_len=kv_len)
    return out


def _packed_vjp_fwd(q, k, v, nh, scale, causal, bq, bk, interpret,
                    kv_len=None):
    out, lse = _packed_flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq,
                                 bk=bk, interpret=interpret, nh=nh,
                                 kv_len=kv_len)
    return out, (q, k, v, out, lse)


def _packed_vjp_bwd(nh, scale, causal, bq, bk, interpret, kv_len, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _packed_flash_bwd(q, k, v, out, lse, g, scale=scale,
                                   causal=causal, bq=bq, bk=bk,
                                   interpret=interpret, nh=nh, kv_len=kv_len)
    return dq, dk, dv


_packed_flash.defvjp(_packed_vjp_fwd, _packed_vjp_bwd)

PACKED_BQ = 256
PACKED_BK = 256


def flash_attention_packed(q, k, v, num_heads: int, causal: bool = False,
                           scale=None, block_q: int = None,
                           block_k: int = None, interpret: bool = False,
                           kv_len: int = None):
    """Flash attention on PACKED [B, S, num_heads*128] arrays.

    Zero layout transposes: inputs are the projection outputs as-is, and
    dq/dk/dv come back in the same layout for the projection weight grads.
    Requires head_dim == 128. Falls back to the [B,S,H,D] kernel via
    reshape when the shape constraints don't hold.

    Measured on v5e (GPT-1.3B B=3 S=2048): parity with the head-major
    kernel at best (73.4% vs 73.3-73.7% MFU across block configs) — the
    ~11 boundary layout passes the packed form eliminates turn out to
    OVERLAP with MXU work in the XLA schedule, while the in-kernel head
    loop (16 lane-sliced dots per block, 16M scoped-VMEM ceiling forcing
    256-row blocks) gives the saving back. Kept as an opt-in
    (PADDLE_TPU_FLASH_PACKED=1 routes GPT through it) for hardware where
    the trade lands differently; the head-major kernel stays the default.
    """
    b, s_q, H = q.shape
    hd = H // num_heads
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    s_k = k.shape[1]
    if kv_len is not None and kv_len <= 0:
        raise ValueError(f"flash_attention_packed: kv_len must be positive, "
                         f"got {kv_len}")
    if kv_len is not None and kv_len >= s_k:
        kv_len = None
    bq = block_q or min(PACKED_BQ, s_q)
    bk = block_k or min(PACKED_BK, s_k)
    bq = min(bq, s_q)
    bk = min(bk, s_k)
    while s_q % bq:
        bq //= 2
    while s_k % bk:
        bk //= 2
    if hd != 128 or bq < 8 or bk < 8:
        q4 = q.reshape(b, s_q, num_heads, hd)
        k4 = k.reshape(b, s_k, num_heads, hd)
        v4 = v.reshape(b, s_k, num_heads, hd)
        out = flash_attention(q4, k4, v4, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret, kv_len=kv_len)
        return out.reshape(b, s_q, H)
    return _packed_flash(q, k, v, int(num_heads), float(scale), bool(causal),
                         int(bq), int(bk), bool(interpret),
                         None if kv_len is None else int(kv_len))
