"""Pallas flash attention for TPU — forward AND blockwise backward.

Beyond-reference capability (SURVEY §5.7: the reference snapshot has no flash
attention — its fused_attention_op.cu materializes the full S×S probability
matrix). Both passes compute attention blockwise with an online/stored
softmax so HBM traffic is O(S·D) instead of O(S²): Q tiles stay resident in
VMEM, K/V stream through in block-sized chunks, and the MXU sees [BQ,D]x
[D,BK] matmuls.

Backward follows FlashAttention-2: the forward additionally writes the
per-row logsumexp L; backward recomputes P = exp(QK^T·scale − L) tile by
tile, with Δ = rowsum(dO ⊙ O) precomputed, and runs two kernels — one
gridded over Q blocks (dQ), one over K blocks (dK, dV) — so nothing O(S²)
is ever materialized in either pass.

Layout: [batch, seq, heads, head_dim] in, same out (paddle convention).
head_dim is padded to the 128-lane boundary inside the wrapper (zero pads
contribute nothing to the dots), so 64-dim heads work.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401 (platform hint)

DEFAULT_BQ = 256
DEFAULT_BK = 256
_NEG = -1e30


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bk):
    """One (batch*head, q_block) program: online-softmax over K/V blocks."""
    qi = pl.program_id(1)
    q = q_ref[0]                                       # [BQ, D] native dtype
    bq = q.shape[0]
    s_k = k_ref.shape[1]
    n_kb = s_k // bk

    m0 = jnp.full((bq, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)

    if causal:
        upper = lax.div((qi + 1) * bq + bk - 1, bk)
        upper = jnp.minimum(upper, n_kb)
    else:
        upper = n_kb

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(ki * bk, bk), :]                       # [BK, D]
        v = v_ref[0, pl.ds(ki * bk, bk), :]                       # [BK, D]
        # bf16xbf16 -> f32 dot: full MXU rate, f32 accumulation
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_idx >= k_idx, s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = corr * l + p.sum(axis=-1, keepdims=True)
        acc_new = corr * acc + jnp.dot(p.astype(v.dtype), v,
                                       preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # logsumexp of scaled scores; backward recomputes p = exp(s - L).
    # Stored replicated over 8 sublanes: TPU blocks need their last two dims
    # tiled (8, 128), so the stats array is [bh, 8, s_q]
    lse_ref[0] = jnp.broadcast_to((m + jnp.log(l))[:, 0][None, :],
                                  (8, q.shape[0]))


def _flash_fwd(q, k, v, *, scale, causal, bq, bk, interpret):
    b, s_q, h, d = q.shape
    s_k = k.shape[1]
    qt = jnp.moveaxis(q, 2, 1).reshape(b * h, s_q, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * h, s_k, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * h, s_k, d)

    grid = (b * h, s_q // bq)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bk=bk),
        out_shape=(jax.ShapeDtypeStruct((b * h, s_q, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, 8, s_q), jnp.float32)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, bq, d), lambda bh, qi: (bh, qi, 0)),
                   pl.BlockSpec((1, 8, bq), lambda bh, qi: (bh, 0, qi))),
        interpret=interpret,
    )(qt, kt, vt)
    return out, lse, (qt, kt, vt)


# ----------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, bk):
    """Grid (bh, q_block): dQ tile = Σ_k ds·K·scale,
    ds = p ⊙ (dO·Vᵀ − Δ)."""
    qi = pl.program_id(1)
    q = q_ref[0]                                        # [BQ, D]
    do = do_ref[0]                                      # [BQ, D]
    lse = lse_ref[0, 0][:, None]                        # [BQ, 1]
    delta = delta_ref[0, 0][:, None]                    # [BQ, 1]
    bq = q.shape[0]
    s_k = k_ref.shape[1]
    n_kb = s_k // bk
    if causal:
        upper = jnp.minimum(lax.div((qi + 1) * bq + bk - 1, bk), n_kb)
    else:
        upper = n_kb

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * bk, bk), :]
        v = v_ref[0, pl.ds(ki * bk, bk), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_idx >= k_idx, s, _NEG)
        p = jnp.exp(s - lse)                             # [BQ, BK]
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, upper, body,
                       jnp.zeros(q.shape, jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, bq):
    """Grid (bh, k_block): dK/dV tiles accumulate over Q blocks."""
    ki = pl.program_id(1)
    k = k_ref[0]                                        # [BK, D]
    v = v_ref[0]                                        # [BK, D]
    bk = k.shape[0]
    s_q = q_ref.shape[1]
    n_qb = s_q // bq
    # causal: only q blocks whose end is >= this k block's start contribute
    lower = lax.div(ki * bk, bq) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * bq, bq), :]                       # [BQ, D]
        do = do_ref[0, pl.ds(qi * bq, bq), :]
        lse = lse_ref[0, 0, pl.ds(qi * bq, bq)][:, None]
        delta = delta_ref[0, 0, pl.ds(qi * bq, bq)][:, None]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * scale
        if causal:
            q_idx = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_idx = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_idx >= k_idx, s, _NEG)
        p = jnp.exp(s - lse).astype(do.dtype)            # [BQ, BK]
        dv_new = dv + jnp.dot(p.T, do, preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p.astype(jnp.float32) * (dp - delta)).astype(q.dtype)  # [BQ, BK]
        dk_new = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = lax.fori_loop(lower, n_qb, body,
                           (jnp.zeros(k.shape, jnp.float32),
                            jnp.zeros(v.shape, jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd(res, g, *, scale, causal, bq, bk, interpret):
    qt, kt, vt, out, lse = res
    bh, s_q, d = qt.shape
    s_k = kt.shape[1]
    dot = jnp.moveaxis(g, 2, 1).reshape(bh, s_q, d)
    delta = jnp.sum(dot.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, None, :], (bh, 8, s_q))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, bk=bk),
        out_shape=jax.ShapeDtypeStruct((bh, s_q, d), qt.dtype),
        grid=(bh, s_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, s_k, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, s_k, d), lambda b, qi: (b, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda b, qi: (b, qi, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, qi: (b, 0, qi)),
            pl.BlockSpec((1, 8, bq), lambda b, qi: (b, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda b, qi: (b, qi, 0)),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, bq=bq),
        out_shape=(jax.ShapeDtypeStruct((bh, s_k, d), kt.dtype),
                   jax.ShapeDtypeStruct((bh, s_k, d), vt.dtype)),
        grid=(bh, s_k // bk),
        in_specs=[
            pl.BlockSpec((1, s_q, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((1, bk, d), lambda b, ki: (b, ki, 0)),
            pl.BlockSpec((1, s_q, d), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, 8, s_q), lambda b, ki: (b, 0, 0)),
            pl.BlockSpec((1, 8, s_q), lambda b, ki: (b, 0, 0)),
        ],
        out_specs=(pl.BlockSpec((1, bk, d), lambda b, ki: (b, ki, 0)),
                   pl.BlockSpec((1, bk, d), lambda b, ki: (b, ki, 0))),
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- custom_vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, bq, bk, interpret):
    out, _, _ = _flash_fwd(q, k, v, scale=scale, causal=causal, bq=bq, bk=bk,
                           interpret=interpret)
    b, s_q, h, d = q.shape
    return jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)


def _flash_vjp_fwd(q, k, v, scale, causal, bq, bk, interpret):
    out, lse, (qt, kt, vt) = _flash_fwd(q, k, v, scale=scale, causal=causal,
                                        bq=bq, bk=bk, interpret=interpret)
    b, s_q, h, d = q.shape
    o = jnp.moveaxis(out.reshape(b, h, s_q, d), 1, 2)
    return o, (qt, kt, vt, out, lse, (b, h))


def _flash_vjp_bwd(scale, causal, bq, bk, interpret, res, g):
    qt, kt, vt, out, lse, (b, h) = res
    dq, dk, dv = _flash_bwd((qt, kt, vt, out, lse), g, scale=scale,
                            causal=causal, bq=bq, bk=bk, interpret=interpret)
    s_q, s_k, d = dq.shape[1], dk.shape[1], dq.shape[2]
    dq = jnp.moveaxis(dq.reshape(b, h, s_q, d), 1, 2)
    dk = jnp.moveaxis(dk.reshape(b, h, s_k, d), 1, 2)
    dv = jnp.moveaxis(dv.reshape(b, h, s_k, d), 1, 2)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def _reference(q, k, v, *, scale, causal):
    from ..attention import attention_reference
    return attention_reference(q, k, v, is_causal=causal, scale=scale)


def flash_attention(q, k, v, causal: bool = False, scale=None,
                    block_q: int = None, block_k: int = None,
                    interpret: bool = False):
    """Differentiable flash attention on [B, S, H, D] arrays.

    head_dim pads to the next 128-lane multiple (zeros change no dot
    product); seq lengths must divide by the chosen blocks, else blocks
    shrink, else the XLA reference path takes over.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s_q, s_k = q.shape[1], k.shape[1]
    bq = block_q or min(DEFAULT_BQ, s_q)
    bk = block_k or min(DEFAULT_BK, s_k)
    while s_q % bq:
        bq //= 2
    while s_k % bk:
        bk //= 2
    if bq < 8 or bk < 8:
        return _reference(q, k, v, scale=scale, causal=causal)
    d = q.shape[-1]
    pad = (-d) % 128
    if pad:
        cfg = [(0, 0)] * 3 + [(0, pad)]
        q = jnp.pad(q, cfg)
        k = jnp.pad(k, cfg)
        v = jnp.pad(v, cfg)
    out = _flash(q, k, v, float(scale), bool(causal), int(bq), int(bk),
                 bool(interpret))
    return out[..., :d] if pad else out
