from . import attention  # noqa: F401
from . import ring_attention  # noqa: F401
