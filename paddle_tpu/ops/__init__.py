from . import attention  # noqa: F401
