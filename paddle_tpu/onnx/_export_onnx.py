"""Literal .onnx serialization for the common feed-forward layer set.

Reference capability: python/paddle/onnx/export.py (delegating to
paddle2onnx's full converter). This module implements the interchange
format directly for the layers that cover MLP/LeNet/VGG-class inference
models: Linear->Gemm, Conv2D->Conv, BatchNorm2D->BatchNormalization,
ReLU/Tanh/Sigmoid/Softmax, MaxPool2D/AvgPool2D, Flatten, Dropout (elided
at inference), and Sequential composition. Anything richer exports the
TPU-native StableHLO artifact instead (paddle_tpu.inference serves it).

The schema is compiled on first use from onnx_subset.proto (the public
ONNX wire contract, subset) via protoc into real protobuf bindings — no
hand-rolled wire encoding. Where the protoc BINARY is absent (the python
google.protobuf runtime alone is enough), the same schema is built at
runtime as a FileDescriptorProto + message_factory — identical messages,
identical wire bytes, no generated code on disk.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import sys
import tempfile

import numpy as np

_PB = None


def _proto():
    """The ONNX subset schema bindings (cached per process): protoc-generated
    when the binary exists, runtime-descriptor-built otherwise."""
    global _PB
    if _PB is not None:
        return _PB
    if shutil.which("protoc"):
        try:
            _PB = _proto_protoc()
            return _PB
        except Exception:
            pass
    _PB = _proto_runtime()
    return _PB


def _proto_protoc():
    """Compile + import the schema via the protoc binary."""
    here = os.path.dirname(os.path.abspath(__file__))
    out = os.path.join(tempfile.gettempdir(),
                       f"ptpu_onnx_pb_{os.getuid()}")
    os.makedirs(out, exist_ok=True)
    gen = os.path.join(out, "onnx_subset_pb2.py")
    src = os.path.join(here, "onnx_subset.proto")
    if not os.path.exists(gen) or \
            os.path.getmtime(gen) < os.path.getmtime(src):
        r = subprocess.run(["protoc", f"--python_out={out}", "-I", here, src],
                           capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"protoc failed for ONNX schema: {r.stderr}")
    if out not in sys.path:
        sys.path.insert(0, out)
    import onnx_subset_pb2 as PB  # noqa: E402
    return PB


class _Namespace:
    pass


def _proto_runtime():
    """Pure-python bindings: the onnx_subset.proto schema expressed as a
    FileDescriptorProto (field numbers ARE the normative ONNX wire
    contract — keep in lockstep with the .proto file), realized through
    google.protobuf.message_factory."""
    from google.protobuf import descriptor_pb2 as dpb
    from google.protobuf import message_factory

    F = dpb.FieldDescriptorProto
    pkg = "paddle_tpu_onnx"
    ref = f".{pkg}."
    f = dpb.FileDescriptorProto(name="onnx_subset_runtime.proto",
                                package=pkg, syntax="proto3")

    def field(m, name, num, ftype, repeated=False, type_name=None,
              oneof=None):
        fd = m.field.add()
        fd.name, fd.number, fd.type = name, num, ftype
        fd.label = F.LABEL_REPEATED if repeated else F.LABEL_OPTIONAL
        if type_name:
            fd.type_name = type_name
        if oneof is not None:
            fd.oneof_index = oneof

    def enum(m, name, values):
        e = m.enum_type.add()
        e.name = name
        for i, nm in enumerate(values):
            v = e.value.add()
            v.name, v.number = nm, i

    a = f.message_type.add(); a.name = "AttributeProto"  # noqa: E702
    enum(a, "AttributeType", ("UNDEFINED", "FLOAT", "INT", "STRING",
                              "TENSOR", "GRAPH", "FLOATS", "INTS",
                              "STRINGS"))
    field(a, "name", 1, F.TYPE_STRING)
    field(a, "f", 2, F.TYPE_FLOAT)
    field(a, "i", 3, F.TYPE_INT64)
    field(a, "s", 4, F.TYPE_BYTES)
    field(a, "t", 5, F.TYPE_MESSAGE, type_name=ref + "TensorProto")
    field(a, "floats", 7, F.TYPE_FLOAT, repeated=True)
    field(a, "ints", 8, F.TYPE_INT64, repeated=True)
    field(a, "strings", 9, F.TYPE_BYTES, repeated=True)
    field(a, "type", 20, F.TYPE_ENUM,
          type_name=ref + "AttributeProto.AttributeType")

    vi = f.message_type.add(); vi.name = "ValueInfoProto"  # noqa: E702
    field(vi, "name", 1, F.TYPE_STRING)
    field(vi, "type", 2, F.TYPE_MESSAGE, type_name=ref + "TypeProto")

    nd = f.message_type.add(); nd.name = "NodeProto"  # noqa: E702
    field(nd, "input", 1, F.TYPE_STRING, repeated=True)
    field(nd, "output", 2, F.TYPE_STRING, repeated=True)
    field(nd, "name", 3, F.TYPE_STRING)
    field(nd, "op_type", 4, F.TYPE_STRING)
    field(nd, "attribute", 5, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "AttributeProto")
    field(nd, "doc_string", 6, F.TYPE_STRING)
    field(nd, "domain", 7, F.TYPE_STRING)

    mo = f.message_type.add(); mo.name = "ModelProto"  # noqa: E702
    field(mo, "ir_version", 1, F.TYPE_INT64)
    field(mo, "producer_name", 2, F.TYPE_STRING)
    field(mo, "producer_version", 3, F.TYPE_STRING)
    field(mo, "domain", 4, F.TYPE_STRING)
    field(mo, "model_version", 5, F.TYPE_INT64)
    field(mo, "doc_string", 6, F.TYPE_STRING)
    field(mo, "graph", 7, F.TYPE_MESSAGE, type_name=ref + "GraphProto")
    field(mo, "opset_import", 8, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "OperatorSetIdProto")

    g = f.message_type.add(); g.name = "GraphProto"  # noqa: E702
    field(g, "node", 1, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "NodeProto")
    field(g, "name", 2, F.TYPE_STRING)
    field(g, "initializer", 5, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "TensorProto")
    field(g, "doc_string", 10, F.TYPE_STRING)
    field(g, "input", 11, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "ValueInfoProto")
    field(g, "output", 12, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "ValueInfoProto")
    field(g, "value_info", 13, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "ValueInfoProto")

    t = f.message_type.add(); t.name = "TensorProto"  # noqa: E702
    enum(t, "DataType", ("UNDEFINED", "FLOAT", "UINT8", "INT8", "UINT16",
                         "INT16", "INT32", "INT64", "STRING", "BOOL",
                         "FLOAT16", "DOUBLE"))
    field(t, "dims", 1, F.TYPE_INT64, repeated=True)
    field(t, "data_type", 2, F.TYPE_INT32)
    field(t, "float_data", 4, F.TYPE_FLOAT, repeated=True)
    field(t, "int32_data", 5, F.TYPE_INT32, repeated=True)
    field(t, "int64_data", 7, F.TYPE_INT64, repeated=True)
    field(t, "name", 8, F.TYPE_STRING)
    field(t, "raw_data", 9, F.TYPE_BYTES)

    ts = f.message_type.add(); ts.name = "TensorShapeProto"  # noqa: E702
    dim = ts.nested_type.add(); dim.name = "Dimension"  # noqa: E702
    od = dim.oneof_decl.add(); od.name = "value"  # noqa: E702
    field(dim, "dim_value", 1, F.TYPE_INT64, oneof=0)
    field(dim, "dim_param", 2, F.TYPE_STRING, oneof=0)
    field(ts, "dim", 1, F.TYPE_MESSAGE, repeated=True,
          type_name=ref + "TensorShapeProto.Dimension")

    tp = f.message_type.add(); tp.name = "TypeProto"  # noqa: E702
    tpt = tp.nested_type.add(); tpt.name = "Tensor"  # noqa: E702
    field(tpt, "elem_type", 1, F.TYPE_INT32)
    field(tpt, "shape", 2, F.TYPE_MESSAGE,
          type_name=ref + "TensorShapeProto")
    field(tp, "tensor_type", 1, F.TYPE_MESSAGE,
          type_name=ref + "TypeProto.Tensor")

    op = f.message_type.add(); op.name = "OperatorSetIdProto"  # noqa: E702
    field(op, "domain", 1, F.TYPE_STRING)
    field(op, "version", 2, F.TYPE_INT64)

    msgs = message_factory.GetMessages([f])
    ns = _Namespace()
    for full_name, cls in msgs.items():
        setattr(ns, full_name.rsplit(".", 1)[-1], cls)
    return ns


def _np(t):
    return np.asarray(t._data if hasattr(t, "_data") else t, np.float32)


class _Builder:
    def __init__(self, PB):
        self.PB = PB
        self.model = PB.ModelProto()
        self.model.ir_version = 8
        self.model.producer_name = "paddle_tpu"
        op = self.model.opset_import.add()
        op.domain = ""
        op.version = 13
        self.g = self.model.graph
        self.g.name = "paddle_tpu_graph"
        self.n = 0

    def tensor(self, name, arr, dtype=np.float32):
        t = self.g.initializer.add()
        t.name = name
        arr = np.ascontiguousarray(arr, dtype)
        t.dims.extend(arr.shape)
        t.data_type = (self.PB.TensorProto.INT64 if dtype == np.int64
                       else self.PB.TensorProto.FLOAT)
        t.raw_data = arr.tobytes()
        return name

    def i64(self, vals):
        return self.tensor(f"i{self.n}_{len(self.g.initializer)}",
                           np.asarray(vals, np.int64), np.int64)

    def scalar(self, v):
        return self.tensor(f"c{self.n}_{len(self.g.initializer)}",
                           np.asarray(v, np.float32))

    def io(self, coll, name, shape):
        vi = coll.add()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = self.PB.TensorProto.FLOAT
        for d in shape:
            dim = tt.shape.dim.add()
            if d is None or (isinstance(d, int) and d < 0):
                dim.dim_param = "N"
            else:
                dim.dim_value = int(d)

    def node(self, op_type, inputs, n_out=1, **attrs):
        nd = self.g.node.add()
        nd.op_type = op_type
        nd.name = f"{op_type}_{self.n}"
        outs = [f"t{self.n}_{i}" for i in range(n_out)]
        self.n += 1
        nd.input.extend(inputs)
        nd.output.extend(outs)
        for k, v in attrs.items():
            a = nd.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.type = self.PB.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, int):
                a.type = self.PB.AttributeProto.INT
                a.i = v
            elif isinstance(v, (list, tuple)):
                a.type = self.PB.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            else:
                raise TypeError(f"attr {k}={v!r}")
        return outs[0] if n_out == 1 else outs


def _pair(v, what="stride/padding"):
    if isinstance(v, int):
        return (v, v)
    if isinstance(v, (list, tuple)) and len(v) == 2 and \
            all(isinstance(e, int) for e in v):
        return tuple(v)
    raise NotImplementedError(
        f"onnx.export: {what} form {v!r} is not supported by the built-in "
        "converter (int or [h, w] ints only — no 'SAME'/'VALID' strings, "
        "4-element, or per-side nested paddings)")


# ---------------------------------------------------------- transformer ops
# Opset-13 building blocks for encoder models (VERDICT r3 #9): everything
# decomposes to standard nodes — LayerNorm to ReduceMean/Sub/Mul/Sqrt/Div,
# tanh-GELU to Pow/Mul/Add/Tanh — so the artifact needs no contrib domains.

def _mm_bias(b, x, weight, bias):
    """[.., in] @ [in, out] + bias via MatMul/Add (Gemm is rank-2-only)."""
    w = b.tensor(f"w{b.n}", _np(weight))
    y = b.node("MatMul", [x, w])
    if bias is not None:
        y = b.node("Add", [y, b.tensor(f"b{b.n}", _np(bias))])
    return y


def _ln(b, x, weight, bias, eps):
    """LayerNorm over the last axis, decomposed to primitive nodes."""
    mu = b.node("ReduceMean", [x], axes=[-1], keepdims=1)
    xc = b.node("Sub", [x, mu])
    var = b.node("ReduceMean", [b.node("Mul", [xc, xc])], axes=[-1],
                 keepdims=1)
    std = b.node("Sqrt", [b.node("Add", [var, b.scalar(eps)])])
    y = b.node("Div", [xc, std])
    y = b.node("Mul", [y, b.tensor(f"g{b.n}", _np(weight))])
    return b.node("Add", [y, b.tensor(f"b{b.n}", _np(bias))])


def _gelu_tanh(b, x):
    """paddle F.gelu(approximate=True): 0.5x(1+tanh(√(2/π)(x+0.044715x³)))."""
    x3 = b.node("Pow", [x, b.scalar(3.0)])
    inner = b.node("Add", [x, b.node("Mul", [x3, b.scalar(0.044715)])])
    t = b.node("Tanh", [b.node("Mul", [inner, b.scalar(0.7978845608028654)])])
    return b.node("Mul", [b.node("Mul", [x, b.scalar(0.5)]),
                          b.node("Add", [t, b.scalar(1.0)])])


def _packed_attention(b, layer, x, s, causal=False):
    """Packed-QKV attention inference graph (models/bert.py BertAttention /
    models/gpt.py GPTSelfAttention — both pack [q|k|v] along the last dim):
    packed qkv MatMul → per-third Slice → [B,S,nh,hd] Reshape → head-major
    Transpose → QKᵀ·scale (+ causal mask) → Softmax → PV → repack → out
    proj. causal=True adds the teacher-forcing decoder mask as a static
    [1,1,S,S] initializer (reference: paddle2onnx's decoder path over
    python/paddle/onnx/export.py:22)."""
    nh, hd = layer.num_heads, layer.head_dim
    if s is None:
        raise ValueError(
            "onnx.export: transformer blocks need a STATIC sequence "
            "length in "
            "input_spec (e.g. [None, 128, hidden]) — the attention Reshape "
            "bakes it into the graph; only the batch dim may be symbolic")
    H = nh * hd
    qkv = _mm_bias(b, x, layer.qkv.weight, getattr(layer.qkv, "bias", None))
    heads = []
    for t in range(3):
        third = b.node("Slice", [qkv, b.i64([t * H]), b.i64([(t + 1) * H]),
                                 b.i64([2])])
        r = b.node("Reshape", [third, b.i64([0, s, nh, hd])])
        heads.append(r)
    q = b.node("Transpose", [heads[0]], perm=[0, 2, 1, 3])   # [B,nh,S,hd]
    kT = b.node("Transpose", [heads[1]], perm=[0, 2, 3, 1])  # [B,nh,hd,S]
    v = b.node("Transpose", [heads[2]], perm=[0, 2, 1, 3])
    scores = b.node("Mul", [b.node("MatMul", [q, kT]),
                            b.scalar(1.0 / float(np.sqrt(hd)))])
    if causal:
        # one shared [1,1,S,S] initializer per seq length: a 24-block
        # decoder reuses it instead of embedding ~4MB per block
        key = getattr(b, "_cmask", {}).get(s)
        if key is None:
            mask = np.triu(np.full((1, 1, s, s), -1e9, np.float32), k=1)
            key = b.tensor(f"cmask{b.n}", mask)
            b._cmask = {**getattr(b, "_cmask", {}), s: key}
        scores = b.node("Add", [scores, key])
    probs = b.node("Softmax", [scores], axis=-1)
    ctx = b.node("MatMul", [probs, v])                       # [B,nh,S,hd]
    ctx = b.node("Transpose", [ctx], perm=[0, 2, 1, 3])
    ctx = b.node("Reshape", [ctx, b.i64([0, s, H])])
    return _mm_bias(b, ctx, layer.out.weight,
                    getattr(layer.out, "bias", None))



def _emit(layer, b: _Builder, x: str) -> str:
    """Map one Layer to ONNX node(s); returns the output tensor name."""
    kind = type(layer).__name__
    if kind in ("Sequential", "LayerList"):
        for sub in layer:
            x = _emit(sub, b, x)
        return x
    if kind == "LayerNorm":
        return _ln(b, x, layer.weight, layer.bias, float(layer._epsilon))
    if kind == "GELU":
        if getattr(layer, "_kw", {}).get("approximate", False):
            return _gelu_tanh(b, x)
        # exact gelu: 0.5·x·(1 + erf(x/√2))
        e = b.node("Erf", [b.node("Div", [x, b.scalar(1.4142135623730951)])])
        return b.node("Mul", [b.node("Mul", [x, b.scalar(0.5)]),
                              b.node("Add", [e, b.scalar(1.0)])])
    if kind == "GPTBlock":
        # pre-LN DECODER block with causal teacher-forcing attention
        # (models/gpt.py GPTBlock.forward, cache-free branch)
        if getattr(layer, "is_moe", False):
            raise NotImplementedError(
                "onnx.export: MoE GPT blocks have no ONNX mapping (routed "
                "dispatch); export the StableHLO artifact instead")
        s = b.seq_len
        h = _ln(b, x, layer.ln_1.weight, layer.ln_1.bias,
                float(layer.ln_1._epsilon))
        x = b.node("Add", [x, _packed_attention(b, layer.attn, h, s,
                                                causal=True)])
        h2 = _ln(b, x, layer.ln_2.weight, layer.ln_2.bias,
                 float(layer.ln_2._epsilon))
        up = _mm_bias(b, h2, layer.mlp.up.weight,
                      getattr(layer.mlp.up, "bias", None))
        y = _mm_bias(b, _gelu_tanh(b, up), layer.mlp.down.weight,
                     getattr(layer.mlp.down, "bias", None))
        return b.node("Add", [x, y])
    if kind == "BertLayer":
        # post-LN encoder block (models/bert.py BertLayer.forward);
        # reference analog: paddle2onnx's transformer path over
        # incubate/nn/layer/fused_transformer.py:725 encoders
        s = b.seq_len
        attn = _packed_attention(b, layer.attention, x, s)
        x = _ln(b, b.node("Add", [x, attn]), layer.ln_1.weight,
                layer.ln_1.bias, float(layer.ln_1._epsilon))
        up = _mm_bias(b, x, layer.up.weight, getattr(layer.up, "bias", None))
        y = _mm_bias(b, _gelu_tanh(b, up), layer.down.weight,
                     getattr(layer.down, "bias", None))
        return _ln(b, b.node("Add", [x, y]), layer.ln_2.weight,
                   layer.ln_2.bias, float(layer.ln_2._epsilon))
    if kind == "Linear":
        w = b.tensor(f"w{b.n}", _np(layer.weight))          # [in, out]
        ins = [x, w]
        if getattr(layer, "bias", None) is not None:
            ins.append(b.tensor(f"b{b.n}", _np(layer.bias)))
        return b.node("Gemm", ins, alpha=1.0, beta=1.0, transB=0)
    if kind == "Conv2D":
        w = b.tensor(f"w{b.n}", _np(layer.weight))          # [O, I/g, kh, kw]
        ins = [x, w]
        if getattr(layer, "bias", None) is not None:
            ins.append(b.tensor(f"b{b.n}", _np(layer.bias)))
        s = _pair(getattr(layer, "_stride", 1), "stride")
        p = _pair(getattr(layer, "_padding", 0), "padding")
        d = _pair(getattr(layer, "_dilation", 1), "dilation")
        g = int(getattr(layer, "_groups", 1))
        return b.node("Conv", ins, strides=list(s),
                      pads=[p[0], p[1], p[0], p[1]], dilations=list(d),
                      group=g)
    if kind in ("BatchNorm2D", "BatchNorm1D", "BatchNorm"):
        scale = b.tensor(f"g{b.n}", _np(layer.weight))
        bias = b.tensor(f"b{b.n}", _np(layer.bias))
        mean = b.tensor(f"m{b.n}", _np(layer._mean))
        var = b.tensor(f"v{b.n}", _np(layer._variance))
        return b.node("BatchNormalization", [x, scale, bias, mean, var],
                      epsilon=float(layer._epsilon))
    if kind == "ReLU":
        return b.node("Relu", [x])
    if kind == "Tanh":
        return b.node("Tanh", [x])
    if kind == "Sigmoid":
        return b.node("Sigmoid", [x])
    if kind == "Softmax":
        return b.node("Softmax", [x],
                      axis=int(getattr(layer, "_kw", {}).get("axis", -1)))
    if kind == "Flatten":
        # ONNX Flatten(axis) collapses to RANK 2; that matches paddle's
        # Flatten only for the (default) start_axis=1, stop_axis=-1 form
        if getattr(layer, "stop_axis", -1) != -1 or \
                getattr(layer, "start_axis", 1) != 1:
            raise NotImplementedError(
                "onnx.export Flatten supports start_axis=1/stop_axis=-1 "
                "only (ONNX Flatten always produces a rank-2 tensor)")
        return b.node("Flatten", [x], axis=1)
    if kind == "MaxPool2D":
        if getattr(layer, "ceil_mode", False) or \
                getattr(layer, "return_mask", False):
            raise NotImplementedError(
                "onnx.export MaxPool2D: ceil_mode/return_mask not supported")
        k = _pair(layer.k, "kernel_size")
        st = _pair(layer.s if layer.s is not None else layer.k, "stride")
        p = _pair(getattr(layer, "p", 0), "padding")
        return b.node("MaxPool", [x], kernel_shape=list(k), strides=list(st),
                      pads=[p[0], p[1], p[0], p[1]])
    if kind == "AvgPool2D":
        if getattr(layer, "ceil_mode", False) or \
                getattr(layer, "divisor", None) is not None:
            raise NotImplementedError(
                "onnx.export AvgPool2D: ceil_mode/divisor_override not "
                "supported")
        k = _pair(layer.k, "kernel_size")
        st = _pair(layer.s if layer.s is not None else layer.k, "stride")
        p = _pair(getattr(layer, "p", 0), "padding")
        # paddle `exclusive` == NOT ONNX count_include_pad
        return b.node("AveragePool", [x], kernel_shape=list(k),
                      strides=list(st), pads=[p[0], p[1], p[0], p[1]],
                      count_include_pad=0 if getattr(layer, "exclusive",
                                                     True) else 1)
    if kind in ("Dropout", "Dropout2D"):
        return x                                   # inference: identity
    raise NotImplementedError(
        f"onnx.export: layer {kind} has no ONNX mapping in the built-in "
        "converter (supported: Sequential/Linear/Conv2D/BatchNorm2D/ReLU/"
        "Tanh/Sigmoid/Softmax/Flatten/MaxPool2D/AvgPool2D/Dropout). Export "
        "without the .onnx suffix for the StableHLO artifact instead.")


def export_onnx(layer, path, input_spec):
    """Serialize `layer` to a literal .onnx file (opset 13, float32)."""
    PB = _proto()
    if not input_spec or len(input_spec) != 1:
        raise ValueError("onnx.export supports exactly one input spec")
    spec = input_spec[0]
    shape = list(getattr(spec, "shape", spec))
    b = _Builder(PB)
    # static sequence length for encoder emitters (Reshape shape tensors
    # need it; batch stays symbolic via ONNX Reshape's 0-copy dim)
    b.seq_len = None
    if len(shape) >= 2 and isinstance(shape[1], int) and shape[1] > 0:
        b.seq_len = int(shape[1])
    b.io(b.g.input, "input", shape)
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    try:
        out = _emit(layer, b, "input")
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()
    # output shape via abstract eval on the framework itself
    import jax
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core import autograd

    def fwd(a):
        with autograd.no_grad():
            if type(layer).__name__ == "LayerList":  # no forward of its own
                t = Tensor(a)
                for sub in layer:
                    t = sub(t)
                return t._data
            return layer(Tensor(a))._data

    oshape = jax.eval_shape(
        fwd, jax.ShapeDtypeStruct(
            tuple(1 if (d is None or d < 0) else d for d in shape),
            jnp.float32)).shape
    b.io(b.g.output, out, (None,) + tuple(oshape[1:])
         if (shape and (shape[0] in (None, -1))) else oshape)
    with open(path, "wb") as f:
        f.write(b.model.SerializeToString())
    return path
