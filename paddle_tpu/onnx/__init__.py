"""paddle.onnx analog.

Reference: python/paddle/onnx/export.py — thin wrapper delegating to the
external paddle2onnx package. Here the native deployment artifact is the AOT
StableHLO module (see paddle_tpu.inference): `export` always produces that;
if the optional `onnx` package is importable we additionally note that true
ONNX conversion is not implemented for the XLA path (StableHLO is the
interchange format in this ecosystem — ONNX's role is filled by it).
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference: paddle.onnx.export(layer, path, input_spec, ...).

    A `path` ending in `.onnx` produces a LITERAL ONNX file (opset 13) via
    the built-in converter for the common feed-forward layer set
    (_export_onnx.py: Linear/Conv2D/BatchNorm/activations/pools/Flatten/
    Sequential) — real interchange with the ONNX ecosystem. Any other path
    produces `path`.pdmodel/.pdmeta (serialized StableHLO, loadable by
    paddle_tpu.inference.create_predictor), the TPU-native deploy artifact
    that covers EVERY model the framework can trace."""
    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    if path.endswith(".onnx"):
        from ._export_onnx import export_onnx
        return export_onnx(layer, path, input_spec)
    from ..jit.save_load import save as _jit_save
    _jit_save(layer, path, input_spec=input_spec)
    return path
