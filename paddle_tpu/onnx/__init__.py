"""paddle.onnx analog.

Reference: python/paddle/onnx/export.py — thin wrapper delegating to the
external paddle2onnx package. Here the native deployment artifact is the AOT
StableHLO module (see paddle_tpu.inference): `export` always produces that;
if the optional `onnx` package is importable we additionally note that true
ONNX conversion is not implemented for the XLA path (StableHLO is the
interchange format in this ecosystem — ONNX's role is filled by it).
"""
from __future__ import annotations


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference: paddle.onnx.export(layer, path, input_spec, ...).

    Produces `path`.pdmodel/.pdmeta (serialized StableHLO, loadable by
    paddle_tpu.inference.create_predictor) — the TPU-native equivalent of an
    .onnx file. Raises if the caller demands a literal .onnx artifact."""
    if path.endswith(".onnx"):
        raise NotImplementedError(
            "ONNX serialization is not available in the TPU-native stack; "
            "export produces a StableHLO artifact instead — pass a path "
            "prefix (no .onnx suffix) and serve it with "
            "paddle_tpu.inference.create_predictor")
    from ..jit.save_load import save as _jit_save
    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    _jit_save(layer, path, input_spec=input_spec)
    return path
