"""GraphLint — the facade that runs every static pass over an executable
and turns findings into a report (or, in guard mode, an error) BEFORE the
job runs.

    lint = GraphLint()                        # report mode
    findings = lint.check(fn, *args, donate_argnums=(0,))
    print(findings.table("my_step"))

    GraphLint(mode="error").check(...)        # raise on any active finding

`check` accepts a plain traceable callable (args may be arrays, numpy
arrays, or jax.ShapeDtypeStructs — nothing executes, tracing is abstract)
or an already-jitted function (its own donate_argnums apply). Tracing
runs under the transfer guard, so an implicit `.item()`/`float()` inside
a Layer forward becomes a host_transfer finding naming the layer path
instead of an anonymous tracer error.

`lint_capture()` records the jitted serving executables the framework
builds while the context is active (models' `_gen_cache_get` feeds it):

    with lint_capture() as calls:
        model.prefill_static(...); model.decode_static(...)   # warmup
    findings = lint.check_calls(calls)

which is how the serving engine and the graph_lint CLI audit the real
prefill/decode executables without reconstructing their closures.
"""
from __future__ import annotations

import contextlib
from typing import List, Optional, Sequence, Tuple

import jax

from .findings import (Allowlist, DEFAULT_ALLOWLIST, Finding, Findings,
                       GraphLintError)
from .passes import (baked_const_pass, donation_pass, dtype_promotion_pass,
                     host_transfer_pass)
from .transfer import HostTransferError, transfer_guard

ALL_PASSES = ("host_transfer", "dtype_promotion", "baked_const", "donation")


class GraphLint:
    """Configuration + driver for the static-analysis suite.

    passes: subset of ALL_PASSES to run.
    allowlist: an Allowlist (defaults to the framework's documented
        exceptions); extra entries via `allow` (list of entry dicts).
    mode: "report" returns findings; "error" raises GraphLintError when
        any non-allowlisted finding at/above `fail_on` severity survives.
    upcast_bytes / const_bytes / donate_bytes: size thresholds for the
        dtype-promotion, baked-const and donation-candidate passes.
    """

    def __init__(self, passes: Sequence[str] = ALL_PASSES,
                 allowlist: Optional[Allowlist] = None,
                 allow: Optional[Sequence[dict]] = None,
                 mode: str = "report", fail_on: str = "warn",
                 upcast_bytes: int = 1 << 16,
                 const_bytes: int = 1 << 20,
                 donate_bytes: int = 1 << 20,
                 replicated_bytes: int = 1 << 20,
                 comm_plan=None):
        unknown = set(passes) - set(ALL_PASSES)
        if unknown:
            raise ValueError(f"unknown lint passes: {sorted(unknown)} "
                             f"(available: {ALL_PASSES})")
        if mode not in ("report", "error"):
            raise ValueError(f"mode must be 'report' or 'error', "
                             f"got {mode!r}")
        self.passes = tuple(passes)
        # `is not None`, not truthiness: an EMPTY Allowlist([]) is a
        # legitimate "no exceptions" configuration
        self.allowlist = Allowlist(
            (DEFAULT_ALLOWLIST if allowlist is None else allowlist)
            .entries)
        if allow:
            self.allowlist.entries.extend(dict(e) for e in allow)
        self.mode = mode
        self.fail_on = fail_on
        self.upcast_bytes = upcast_bytes
        self.const_bytes = const_bytes
        self.donate_bytes = donate_bytes
        # sharding lint (ISSUE 15): threshold for the large-replicated-
        # parameter pass, and an optional declared CommPlan every
        # check_sharded call verifies the inventory against
        self.replicated_bytes = replicated_bytes
        self.comm_plan = comm_plan

    @classmethod
    def coerce(cls, value) -> Optional["GraphLint"]:
        """None/False -> None; True -> report-mode lint; "error" ->
        guard-mode lint; a GraphLint passes through. (The TrainStep /
        ServingConfig `lint=` option.)"""
        if value is None or value is False:
            return None
        if value is True:
            return cls()
        if value == "error":
            return cls(mode="error")
        if isinstance(value, cls):
            return value
        raise ValueError(f"lint= expects True/'error'/GraphLint, "
                         f"got {value!r}")

    # ------------------------------------------------------------ check
    def check(self, fn, *args, donate_argnums: Sequence[int] = (),
              name: str = "", guard: bool = True, **kwargs) -> Findings:
        """Run the configured passes over one executable. Abstract: the
        function is traced (and, for the donation pass, lowered), never
        compiled or executed. guard=False skips the error-mode raise —
        for callers that store the findings first and guard themselves."""
        name = name or getattr(fn, "__name__", "fn") or "fn"
        findings = Findings()
        closed = None
        with transfer_guard() as g:
            try:
                closed = jax.make_jaxpr(fn)(*args, **kwargs)
            except HostTransferError:
                findings.extend(g.findings)
            except (jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError,
                    jax.errors.TracerBoolConversionError) as e:
                findings.add(Finding(
                    "host_transfer", "concretization", "error",
                    f"tracing aborted on a concretization the guard "
                    f"could not attribute: {str(e).splitlines()[0]}",
                    executable=name))
        if closed is not None:
            if "host_transfer" in self.passes:
                findings.extend(host_transfer_pass(closed, name))
            if "dtype_promotion" in self.passes:
                findings.extend(dtype_promotion_pass(
                    closed, name, min_bytes=self.upcast_bytes))
            if "baked_const" in self.passes:
                findings.extend(baked_const_pass(
                    closed, name, min_bytes=self.const_bytes))
            # runs even with nothing donated: that is exactly when the
            # "donatable" advisory (large input with a same-shape output)
            # has something to say
            if "donation" in self.passes:
                findings.extend(donation_pass(
                    fn, args, donate_argnums, name,
                    min_bytes=self.donate_bytes, closed_jaxpr=closed,
                    kwargs=kwargs))
        for f in findings:
            if not f.executable:
                f.executable = name
        self.allowlist.apply(findings)
        if guard:
            self._guard(findings, name)
        return findings

    def check_calls(self, calls, dedupe: bool = True,
                    guard: bool = True) -> Findings:
        """Lint executables recorded by `lint_capture` — entries are
        (kind, jitted_fn, (args, kwargs)) with abstract (SDS) args."""
        findings = Findings()
        seen = set()
        for kind, fn, (args, kwargs) in calls:
            name = _kind_name(kind)
            key = (id(fn), name)
            if dedupe and key in seen:
                continue
            seen.add(key)
            # defer the guard until every call is checked
            findings.extend(self.check(fn, *args, name=name,
                                       guard=False, **kwargs))
        if guard:
            self._guard(findings, "captured executables")
        return findings

    # --------------------------------------------------------- sharded
    def check_sharded(self, fn, *args, name: str = "",
                      in_shardings=None, out_shardings=None,
                      donate_argnums: Sequence[int] = (),
                      param_names=None, plan=None, mesh_axes=None,
                      guard: bool = True, **kwargs):
        """Statically audit the SPMD communication plan of an executable
        lowered under a mesh (ISSUE 15): lower + compile (nothing
        executes — CPU host-platform meshes work), then run the
        sharding passes over the post-partitioning HLO — collective
        inventory, partitioner-inserted-resharding detection, the
        large-replicated-parameter pass, and the CommPlan check (`plan`
        or this linter's `comm_plan`).

        `fn` may be an already-jitted function carrying its own
        shardings (the TrainStep path) or a plain callable with
        `in_shardings`/`out_shardings` (NamedShardings — the mesh rides
        in them). Returns a ShardingAudit; its findings pass through
        the allowlist and, in guard mode, trip GraphLintError — plan
        violations raise the sharper CommPlanError."""
        from .commplan import CommPlanError
        from .sharding import audit_hlo, compiled_hlo_text
        name = name or getattr(fn, "__name__", "fn") or "fn"
        if hasattr(fn, "lower") and hasattr(fn, "__wrapped__"):
            jfn = fn
        else:
            jit_kwargs = {}
            if in_shardings is not None:
                jit_kwargs["in_shardings"] = in_shardings
            if out_shardings is not None:
                jit_kwargs["out_shardings"] = out_shardings
            jfn = jax.jit(fn, donate_argnums=tuple(donate_argnums),
                          **jit_kwargs)
        text = compiled_hlo_text(jfn, *args, **kwargs)
        audit = audit_hlo(text, executable=name,
                          param_names=param_names,
                          plan=plan if plan is not None else self.comm_plan,
                          replicated_bytes=self.replicated_bytes,
                          mesh_axes=mesh_axes)
        self.allowlist.apply(audit.findings)
        if guard and self.mode == "error":
            plan_active = audit.findings.for_pass("comm_plan") \
                .active(self.fail_on)
            if plan_active:
                raise CommPlanError(plan_active, name)
        if guard:
            self._guard(audit.findings, name)
        return audit

    def _guard(self, findings: Findings, executable: str):
        if self.mode != "error":
            return
        active = findings.active(self.fail_on)
        if active:
            raise GraphLintError(active, executable)


def _kind_name(kind) -> str:
    if isinstance(kind, tuple) and kind:
        head = str(kind[0])
        rest = ",".join(str(k) for k in kind[1:5])
        return f"{head}[{rest}]" if rest else head
    return str(kind)


def _abstract_leaf(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        # preserve MESH shardings (ISSUE 16): a sharded-serving pool's
        # NamedSharding must survive abstraction or re-lowering the
        # captured executable would silently audit the single-chip
        # program. Single-device placements are dropped deliberately —
        # they carry no SPMD information and would pin the lowering to
        # one device id.
        sh = getattr(x, "sharding", None)
        if isinstance(sh, jax.sharding.NamedSharding):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype,
                                        sharding=sh)
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    return x


@contextlib.contextmanager
def lint_capture():
    """Record every serving executable the framework jits/fetches while
    active (see models' `_gen_cache_get`): yields a list of
    (kind, jitted_fn, (abstract_args, abstract_kwargs)) entries for
    `GraphLint.check_calls`. Capturing is observation only — the calls
    still execute normally (the warmup)."""
    from ..jit import api as _api
    calls: List[Tuple] = []
    prev = _api._lint_capture_sink
    _api._lint_capture_sink = calls
    try:
        yield calls
    finally:
        _api._lint_capture_sink = prev


def _capture_record(sink, kind, fn, args, kwargs):
    """Append one abstract call record (jit/api's wrapper calls this)."""
    a = jax.tree.map(_abstract_leaf, args)
    k = jax.tree.map(_abstract_leaf, kwargs)
    sink.append((kind, fn, (a, k)))
