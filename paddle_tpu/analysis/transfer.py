"""transfer_guard — catch implicit device->host syncs AT TRACE TIME and
name the layer they came from.

The r8 zero-sync claim ("no per-step host transfers in the compiled
step") was proven by inspecting HLO in tests; this makes it a reusable
guard: inside ``with transfer_guard():`` any implicit ``bool()`` /
``float()`` / ``int()`` / ``.item()`` / ``.numpy()`` / ``np.asarray()``
on a TRACER-backed Tensor raises (or records) a HostTransferError that
names the layer path being traced (e.g. ``GPTForCausalLM/gpt/h/0/attn``)
— instead of jax's anonymous ConcretizationTypeError three frames deep.

Mechanics: core.tensor's host-interop methods call a module hook before
touching the data; the guard installs the hook AND wraps
``nn.Layer.__call__`` with a thread-local layer stack so the error can
say WHERE. Both patches are nest-counted and removed when the outermost
guard exits; with no guard active the hook is None and the Tensor
methods pay one ``is None`` check.

Eager tensors are untouched — ``.item()`` on concrete data is a
legitimate host read; the hazard is exactly a tracer-backed one, which
would either crash (control flow) or silently force a per-step transfer
(callbacks).
"""
from __future__ import annotations

import contextlib
import threading
from typing import List, Optional

import jax

from .findings import Finding, Findings

_tls = threading.local()


class HostTransferError(RuntimeError):
    """An implicit device->host transfer happened on a traced value."""

    def __init__(self, message: str, finding: Optional[Finding] = None):
        self.finding = finding
        super().__init__(message)


# ----------------------------------------------------------- layer stack

def _stack() -> List:
    st = getattr(_tls, "layers", None)
    if st is None:
        st = _tls.layers = []
    return st


def _child_name(parent, child) -> Optional[str]:
    """Dotted name of `child` inside `parent` (named_sublayers scan,
    cached per parent — tracing visits each layer once per signature, so
    the scan cost is a trace-time constant)."""
    cache = getattr(_tls, "name_cache", None)
    if cache is None:
        cache = _tls.name_cache = {}
    key = id(parent)
    m = cache.get(key)
    if m is None:
        m = {id(l): n for n, l in parent.named_sublayers()}
        cache[key] = m
    return m.get(id(child))


def current_layer_path() -> str:
    """Qualified path of the layer currently executing forward() under
    the guard ('' when no layer is on the stack — e.g. a bare loss fn)."""
    st = _stack()
    if not st:
        return ""
    parts = [type(st[0]).__name__]
    for i in range(1, len(st)):
        name = _child_name(st[i - 1], st[i])
        parts.append(name.replace(".", "/") if name
                     else type(st[i]).__name__)
    return "/".join(parts)


# ------------------------------------------------------------- patching

_lock = threading.Lock()
_depth = 0
_orig_call = None


def _patched_call(self, *inputs, **kwargs):
    st = _stack()
    st.append(self)
    try:
        return _orig_call(self, *inputs, **kwargs)
    finally:
        st.pop()


def _is_tracer(data) -> bool:
    return isinstance(data, jax.core.Tracer)


def _hook(kind: str, data):
    guard = getattr(_tls, "guard", None)
    if guard is None or not _is_tracer(data):
        return
    guard._on_transfer(kind, data)


class TransferGuard:
    """The active guard object (returned by the context manager).

    Always raises at the offending call — a tracer cannot actually be
    concretized, so the call could never have succeeded; the guard's
    value is the NAMED error (layer path + transfer kind) and the
    Finding it records on ``guard.findings`` before raising (GraphLint
    catches the error and keeps the finding)."""

    def __init__(self):
        self.findings = Findings()

    def _on_transfer(self, kind: str, data):
        path = current_layer_path()
        aval = getattr(data, "aval", None)
        desc = (f"{aval.dtype}{list(aval.shape)}"
                if aval is not None else "traced value")
        f = Finding(
            "host_transfer", f"tracer_{kind}", "error",
            f"implicit host transfer: `{kind}()` on a traced Tensor "
            f"({desc}) — inside a compiled region this is either a "
            f"crash or a per-step device->host sync",
            where=path or "(no layer on stack)")
        self.findings.add(f)
        raise HostTransferError(
            f"transfer_guard: {kind}() called on a tracer-backed Tensor "
            f"({desc}) in layer path "
            f"{path or '<outside any Layer.forward>'} — keep host reads "
            f"out of traced code (use jnp ops / lax.cond), or read after "
            f"the compiled call returns", finding=f)


@contextlib.contextmanager
def transfer_guard():
    """Guard a tracing region (or a whole program) against implicit
    host transfers. Re-entrant; thread-local. Yields the TransferGuard
    (``guard.findings`` holds what was caught before the raise)."""
    global _depth, _orig_call
    from ..core import tensor as _tensor
    from ..nn.layer import Layer

    guard = TransferGuard()
    prev = getattr(_tls, "guard", None)
    with _lock:
        if _depth == 0:
            _orig_call = Layer.__call__
            Layer.__call__ = _patched_call
            _tensor._concretization_hook = _hook
        _depth += 1
    _tls.guard = guard
    try:
        yield guard
    finally:
        _tls.guard = prev
        with _lock:
            _depth -= 1
            if _depth == 0:
                Layer.__call__ = _orig_call
                _tensor._concretization_hook = None
                # _orig_call stays set: a thread mid-_patched_call when
                # the unpatch lands must still reach the real __call__
                # (NULLing it would crash an unrelated forward)
                # drop the sublayer-name caches with the session: id()s
                # recycle across models, and a stale id->name map would
                # mislabel the very layer path this guard exists to name
                _tls.name_cache = {}
