"""Structured findings — the one result schema every static pass emits.

A Finding is one provable (or strongly-indicated) fact about an executable:
a host transfer inside a traced region, a donated buffer XLA could not
alias, a bf16 tensor silently upcast to f32, a closure-captured array baked
into the jaxpr as a const, a signature delta that will force a recompile,
or an invalid serving configuration. Every producer — the jaxpr/HLO passes
(analysis.passes), the recompile differ (analysis.recompile), the transfer
guard (analysis.transfer), and config validation (inference.ServingConfig)
— speaks this schema, so one table renderer, one allowlist format and one
guard-mode error serve the whole suite.

Allowlist: some findings describe DELIBERATE behavior (f32 softmax
accumulation in a bf16 model, the sampling head's f32 logits). An
Allowlist entry is {"pass": <pass name>, "code": <finding code or "*">,
"where": <substring of the finding's location>, "reason": <why this is
fine>} — matched findings stay in the report marked allowed (with the
reason) but never trip guard mode. DEFAULT_ALLOWLIST documents the
framework's own deliberate exceptions.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

#: severity order for guard thresholds
SEVERITIES = ("info", "warn", "error")


@dataclass
class Finding:
    """One static-analysis result.

    pass_name: which pass produced it (host_transfer | donation |
        dtype_promotion | baked_const | recompile_hazard | config |
        source_lint).
    code: short machine-matchable slug within the pass (e.g.
        "donated_unaliased", "bf16_to_f32", "tracer_item").
    severity: "error" (invariant broken), "warn" (probable hazard),
        "info" (advisory, e.g. a donation candidate).
    message: one human sentence; says what AND where.
    where: the location — a source summary ("gpt.py:123 (forward)"), a
        layer path ("GPTForCausalLM/gpt/h/0/attn"), or an argument name.
    executable: name of the audited executable ("decode_static[...]").
    data: pass-specific details (shapes, dtypes, byte counts, indices).
    allowed/allow_reason: set when an Allowlist entry matched.
    """
    pass_name: str
    code: str
    severity: str
    message: str
    where: str = ""
    executable: str = ""
    data: Dict = field(default_factory=dict)
    allowed: bool = False
    allow_reason: Optional[str] = None

    def to_dict(self) -> dict:
        d = {"pass": self.pass_name, "code": self.code,
             "severity": self.severity, "message": self.message}
        if self.where:
            d["where"] = self.where
        if self.executable:
            d["executable"] = self.executable
        if self.data:
            d["data"] = self.data
        if self.allowed:
            d["allowed"] = True
            d["allow_reason"] = self.allow_reason
        return d

    def __str__(self):
        tag = f"[{self.pass_name}:{self.code}]"
        loc = f" @ {self.where}" if self.where else ""
        ex = f" in {self.executable}" if self.executable else ""
        allow = f" (allowed: {self.allow_reason})" if self.allowed else ""
        return f"{self.severity.upper()} {tag} {self.message}{loc}{ex}{allow}"


class Allowlist:
    """Ordered allow entries; first match wins.

    Entries are dicts: {"pass": name, "code": code-or-"*",
    "where": substring-or-"", "reason": text}. `apply` marks matched
    findings allowed in place (the report keeps them — an allowlist is
    documentation, not deletion)."""

    def __init__(self, entries: Optional[Sequence[dict]] = None):
        self.entries = [dict(e) for e in (entries or [])]

    def __len__(self):
        return len(self.entries)

    def add(self, pass_name: str, code: str = "*", where: str = "",
            reason: str = ""):
        self.entries.append({"pass": pass_name, "code": code,
                             "where": where, "reason": reason})
        return self

    def extend(self, other: "Allowlist") -> "Allowlist":
        self.entries.extend(other.entries)
        return self

    def match(self, f: Finding) -> Optional[dict]:
        for e in self.entries:
            if e.get("pass") not in ("*", f.pass_name):
                continue
            if e.get("code", "*") not in ("*", f.code):
                continue
            where = e.get("where", "")
            if where and where not in (f.where or "") \
                    and where not in (f.executable or ""):
                continue
            return e
        return None

    def apply(self, findings: Sequence[Finding]) -> List[Finding]:
        for f in findings:
            e = self.match(f)
            if e is not None:
                f.allowed = True
                f.allow_reason = e.get("reason") or "allowlisted"
        return list(findings)

    @classmethod
    def from_json(cls, path: str) -> "Allowlist":
        with open(path) as fh:
            return cls(json.load(fh))


#: The framework's own documented exceptions — each entry is a deliberate
#: design decision, not an oversight. Format doubles as the user example.
DEFAULT_ALLOWLIST = Allowlist([
    # Sampling runs on f32 logits by design: argmax tie-breaking, top-p
    # cumulative sums and jax.random.categorical all assume f32 — the [B,V]
    # upcast happens once per sampled token, not per layer.
    {"pass": "dtype_promotion", "code": "*", "where": "sample_logits",
     "reason": "next-token sampling is deliberately f32 (argmax ties, "
               "top-p cumsum, categorical)"},
    {"pass": "dtype_promotion", "code": "*", "where": "prefill",
     "reason": "per-row last-real-position logits are gathered in f32 for "
               "the sampling head (one [B,V] row set per prefill)"},
    {"pass": "dtype_promotion", "code": "*", "where": "decode_",
     "reason": "the decode loop reads ONE [B,V] logits row in f32 per "
               "sampled token (sampling-head precision, not a layer "
               "upcast)"},
    {"pass": "dtype_promotion", "code": "*", "where": "generate_static",
     "reason": "the decode loop reads ONE [B,V] logits row in f32 per "
               "sampled token (sampling-head precision, not a layer "
               "upcast)"},
    {"pass": "dtype_promotion", "code": "*", "where": "optimizer.py",
     "reason": "optimizer update math runs in f32 on low-precision "
               "params (master-precision update; moments store f32 or "
               "int8 codes by config)"},
    # Softmax / layernorm / loss accumulate in f32 deliberately — the
    # classic bf16-training exceptions (see ops.attention score_dtype and
    # incubate fused_linear_cross_entropy).
    {"pass": "dtype_promotion", "code": "*", "where": "softmax",
     "reason": "softmax accumulates in f32 (numeric range)"},
    {"pass": "dtype_promotion", "code": "*", "where": "layer_norm",
     "reason": "layernorm moments accumulate in f32"},
    {"pass": "dtype_promotion", "code": "*", "where": "norm.py",
     "reason": "normalization moments accumulate in f32"},
    {"pass": "dtype_promotion", "code": "*", "where": "loss",
     "reason": "loss/CE reductions accumulate in f32"},
    {"pass": "dtype_promotion", "code": "*", "where": "cross_entropy",
     "reason": "CE softmax/logsumexp accumulates in f32"},
    {"pass": "dtype_promotion", "code": "*", "where": "attention",
     "reason": "attention probabilities/score reductions may accumulate "
               "in f32 (score_dtype policy)"},
    {"pass": "dtype_promotion", "code": "*", "where": "train_step.py",
     "reason": "grad-norm/clip/stats reductions accumulate in f32 "
               "(scalar-output reductions of grads)"},
    {"pass": "dtype_promotion", "code": "*", "where": "sentinel.py",
     "reason": "numerics sentinel rows reduce in f32 by design"},
    # Sharding lint (ISSUE 15): under tensor parallelism the partitioner
    # may gather the VOCAB-SHARDED embedding table for the row lookup
    # (and its tied-head/optimizer twins) instead of the masked-lookup+
    # psum form — bounded by vocab x hidden and acceptable at current
    # scales; a shard_map masked lookup is the fix when 50k-vocab tables
    # make this the top ledger row. Scoped to wte so a gather of any
    # OTHER layer's weight still fails lint.
    {"pass": "sharding", "code": "param_gather", "where": "wte",
     "reason": "vocab-parallel embedding lookup: XLA may gather the "
               "table (bounded by vocab x hidden); masked-lookup+psum "
               "via shard_map is the planned fix at real vocab sizes"},
])


class Findings:
    """An ordered collection of Finding with filtering + table rendering."""

    def __init__(self, items: Optional[Sequence[Finding]] = None):
        self.items: List[Finding] = list(items or [])

    def __iter__(self):
        return iter(self.items)

    def __len__(self):
        return len(self.items)

    def __bool__(self):
        return bool(self.items)

    def __getitem__(self, i):
        return self.items[i]

    def add(self, *findings: Finding) -> "Findings":
        self.items.extend(findings)
        return self

    def extend(self, other) -> "Findings":
        self.items.extend(list(other))
        return self

    def for_pass(self, pass_name: str) -> "Findings":
        return Findings([f for f in self.items if f.pass_name == pass_name])

    def active(self, min_severity: str = "warn") -> "Findings":
        """Non-allowlisted findings at/above the severity threshold — the
        set guard mode trips on."""
        lvl = SEVERITIES.index(min_severity)
        return Findings([f for f in self.items if not f.allowed
                         and SEVERITIES.index(f.severity) >= lvl])

    def to_dicts(self) -> List[dict]:
        return [f.to_dict() for f in self.items]

    def grouped(self) -> "Findings":
        """Collapse repeats of one site: findings sharing (pass, code,
        where, executable, allowed) merge into one carrying
        data["count"] — 24 layer_norm rows read as one line, not 24."""
        order, by_key = [], {}
        for f in self.items:
            key = (f.pass_name, f.code, f.where, f.executable, f.allowed)
            g = by_key.get(key)
            if g is None:
                g = Finding(f.pass_name, f.code, f.severity, f.message,
                            where=f.where, executable=f.executable,
                            data=dict(f.data), allowed=f.allowed,
                            allow_reason=f.allow_reason)
                g.data["count"] = 0
                by_key[key] = g
                order.append(g)
            g.data["count"] += 1
        for g in order:
            if g.data["count"] > 1:
                g.message = f"[x{g.data['count']}] {g.message}"
        return Findings(order)

    def table(self, title: Optional[str] = None) -> str:
        """Fixed-width findings table (the CLI output)."""
        lines = []
        if title:
            lines.append(title)
        if not self.items:
            lines.append("  (clean — no findings)")
            return "\n".join(lines)
        rows = []
        for f in self.items:
            sev = f.severity.upper() + ("*" if f.allowed else "")
            rows.append((sev, f"{f.pass_name}:{f.code}",
                         f.executable or "-", f.message
                         + (f" [allowed: {f.allow_reason}]"
                            if f.allowed else "")))
        w0 = max(len(r[0]) for r in rows)
        w1 = max(len(r[1]) for r in rows)
        w2 = min(max(len(r[2]) for r in rows), 28)
        for r in rows:
            lines.append(f"  {r[0]:<{w0}}  {r[1]:<{w1}}  "
                         f"{r[2][:w2]:<{w2}}  {r[3]}")
        return "\n".join(lines)


class GraphLintError(RuntimeError):
    """Guard mode tripped: the executable violates a linted invariant."""

    def __init__(self, findings: Findings, executable: str = ""):
        self.findings = findings
        self.executable = executable
        head = (f"graph lint failed for {executable}: "
                if executable else "graph lint failed: ")
        msg = head + f"{len(findings)} finding(s)\n" + \
            "\n".join(f"  {f}" for f in findings)
        super().__init__(msg)


class ConfigValidationError(ValueError):
    """A configuration the engine cannot serve — carries the same Finding
    schema as the graph passes so tools print WHY, not just that it failed
    (ValueError subclass: existing `except ValueError` callers keep
    working)."""

    def __init__(self, finding: Finding):
        self.finding = finding
        super().__init__(str(finding))
