"""Sharding lint — prove the SPMD communication plan statically, from the
post-partitioning HLO, before the job ever runs.

The runtime side of this story is `profiler.trace_analysis
.collective_rows()`: a per-collective ledger parsed from a captured
device trace — visible only AFTER chips burned a step. This module is
its static twin: lower + compile a jitted executable under a mesh (CPU
host-platform meshes work — `--xla_force_host_platform_device_count=8`),
parse the optimized HLO text, and produce

  collective inventory   one row per collective instruction, SAME row
                         schema as collective_rows() (timing columns
                         None — statics have no clock), with shapes,
                         dtypes, replica groups and statically computed
                         bytes (operand + output buffer bytes per
                         device per execution — the static twin of the
                         trace's `bytes_accessed` stat)
  resharding findings    an all-gather that undoes a parameter's
                         declared sharding (the partitioner quietly
                         gathering a sharded weight to replicated —
                         either a wrong pspec or a layout conflict); the
                         finding names the parameter and the source site
  replication findings   large replicated parameters in an
                         otherwise-tensor-sharded executable, with the
                         pspec that would shard them
  CommPlan check         the inventory diffed against a declared plan
                         (analysis.commplan) — extra/missing collectives
                         are structured errors

`diff_ledgers` closes the loop: the static inventory and the runtime
trace ledger aggregate by collective kind and must agree on bytes —
the static-vs-runtime cross-check tools/graph_lint.py `comm-xcheck`
runs against the checked-in fixture.

Known limits (documented, not silent): instructions inside `while`
bodies are counted once per textual occurrence, not per trip (a scan
over microbatches under-counts); bytes are per-device buffer traffic,
not link-level ring traffic (2(n-1)/n factors are an algorithm choice
the compiler owns).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .commplan import (COLLECTIVE_KINDS, CommPlan, CommPlanError,
                       collective_kind, rows_by_kind)
from .findings import Finding, Findings

#: opcodes the inventory collects ("-start" async halves count; "-done"
#: halves are skipped — same transfer, second mention). ONE list, shared
#: with the plan checker: the inventory and CommPlan must never disagree
#: about what counts as a collective.
_COLLECTIVE_OPS = COLLECTIVE_KINDS

#: ops a value flows through unchanged (modulo layout/dtype) — the walk
#: from an all-gather back to the parameter it gathers
_PASSTHROUGH_OPS = ("copy", "bitcast", "convert", "reshape", "transpose",
                    "get-tuple-element", "optimization-barrier")
#: the subset that appears as words in XLA's generated fusion names
#: ("convert_copy_fusion.2") — a unary fusion named purely from these is
#: itself pass-through (the multi-word ops above never name fusions)
_PASSTHROUGH_FUSION_WORDS = ("convert", "copy", "bitcast", "reshape",
                             "transpose")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3": 1,
    "f8e5m2fnuz": 1, "f8e4m3fnuz": 1,
}

# one typed value in an instruction line: dtype[dims]{optional layout}
_TYPED_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^{}]*\})?")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\(?.*?\)?)\s*"
    r"([\w\-]+)\((.*)$")
_METADATA_RE = re.compile(
    r'metadata=\{[^}]*?op_name="([^"]*)"'
    r'(?:[^}]*?source_file="([^"]*)")?'
    r'(?:[^}]*?source_line=(\d+))?')
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[^}]*(?:\},\{[^}]*)*\}\}|\[[0-9,]+\]<=\[[^\]]*\]"
    r"(?:T\([0-9,]+\))?)")
_PARAM_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\S+)\s*parameter\((\d+)\)"
    r"(?:,\s*sharding=(\{.*?\})(?=,|\s*$))?")


def _shape_dtype(text: str) -> List[Tuple[str, Tuple[int, ...]]]:
    """All (dtype, shape) values in a type string — one entry for a plain
    type, several for a tuple type."""
    out = []
    for m in _TYPED_RE.finditer(text):
        dims = tuple(int(d) for d in m.group(2).split(",") if d)
        out.append((m.group(1), dims))
    return out


def _nbytes(dtype: str, shape: Tuple[int, ...]) -> int:
    return int(np.prod(shape)) * _DTYPE_BYTES.get(dtype, 4) if shape \
        else _DTYPE_BYTES.get(dtype, 4)


def _where_of(meta: Optional[dict]) -> str:
    """The caller-chain `where` convention over HLO metadata: the op's
    source site plus the trailing op_name component ("mpu.py:131
    (dot_general)"). HLO keeps one frame, so the chain is one link."""
    if not meta:
        return ""
    parts = []
    if meta.get("source_file"):
        base = meta["source_file"].rsplit("/", 1)[-1]
        line = meta.get("source_line")
        parts.append(f"{base}:{line}" if line else base)
    op = (meta.get("op_name") or "").rsplit("/", 1)[-1]
    if op:
        parts.append(f"({op})")
    return " ".join(parts)


def _parse_groups(attrs: str) -> Tuple[str, Optional[int], Optional[int]]:
    """(raw string, num_groups, group_size) of a replica_groups attr.
    Handles both the explicit form ``{{0,1},{2,3}}`` and the iota form
    ``[4,2]<=[8]`` / ``[4,2]<=[2,4]T(1,0)``."""
    m = _REPLICA_GROUPS_RE.search(attrs)
    if not m:
        return "", None, None
    raw = m.group(1)
    if raw.startswith("{{"):
        groups = raw[1:-1].split("},{")
        sizes = [len([x for x in g.strip("{}").split(",") if x])
                 for g in groups]
        return raw, len(groups), (sizes[0] if sizes else None)
    gm = re.match(r"\[(\d+),(\d+)\]", raw)
    if gm:
        return raw, int(gm.group(1)), int(gm.group(2))
    return raw, None, None


# ------------------------------------------------------------ HLO parse

@dataclass
class HloCollective:
    """One collective instruction of the optimized module."""
    name: str
    kind: str
    out: List[Tuple[str, Tuple[int, ...]]]        # [(dtype, shape)]
    operands: List[Tuple[str, Tuple[int, ...]]]
    operand_names: List[str]
    replica_groups: str = ""
    num_groups: Optional[int] = None
    group_size: Optional[int] = None
    channel_id: Optional[int] = None
    where: str = ""

    @property
    def bytes(self) -> int:
        """Static per-device bytes per execution: operand + output buffer
        bytes — the twin of the runtime trace's `bytes_accessed` stat."""
        return (sum(_nbytes(d, s) for d, s in self.operands)
                + sum(_nbytes(d, s) for d, s in self.out))


@dataclass
class HloEntryParam:
    """One ENTRY-computation parameter with its compiled sharding."""
    index: int
    hlo_name: str
    dtype: str
    local_shape: Tuple[int, ...]
    sharding: str = ""           # raw sharding attr ("" = none recorded)
    arg_name: str = ""           # keypath from lowering metadata op_name
    global_shape: Optional[Tuple[int, ...]] = None

    @property
    def replicated(self) -> bool:
        return (not self.sharding) or "replicated" in self.sharding \
            or "maximal" in self.sharding

    @property
    def sharded(self) -> bool:
        return not self.replicated

    @property
    def local_bytes(self) -> int:
        return _nbytes(self.dtype, self.local_shape)


def _global_shape(local: Tuple[int, ...], sharding: str
                  ) -> Tuple[int, ...]:
    """Undo the tile assignment: global dim i = local dim i * tiles[i].
    `devices=[a,b,...]` may carry trailing replication tiles
    (last_tile_dim_replicate / last_tile_dims) beyond the rank — only
    the first rank entries partition data dims."""
    m = re.search(r"devices=\[([0-9,]+)\]", sharding or "")
    if not m:
        return tuple(local)
    tiles = [int(x) for x in m.group(1).split(",")]
    return tuple(d * t for d, t in zip(local, tiles[:len(local)]))


def parse_hlo(text: str) -> Tuple[List[HloCollective],
                                  Dict[int, HloEntryParam],
                                  Dict[str, Tuple[str, List[str]]]]:
    """(collectives, entry params by index, def-use map) of one optimized
    HLO module text. The def-use map is {instr_name: (opcode,
    [operand names])} over every computation — enough to walk a value
    chain; bodies/fusion computations are flat in the same namespace."""
    collectives: List[HloCollective] = []
    defs: Dict[str, Tuple[str, List[str]]] = {}
    entries: Dict[int, HloEntryParam] = {}
    in_entry = False
    depth_entry = 0
    for line in text.splitlines():
        if line.startswith("ENTRY"):
            in_entry = True
            depth_entry = 0
            continue
        if in_entry:
            depth_entry += line.count("{") - line.count("}")
            if line.strip() == "}" and depth_entry < 0:
                in_entry = False
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, type_str, opcode, rest = m.groups()
        operand_str = rest.split(")")[0] if ")" in rest else rest
        operand_names = re.findall(r"%([\w.\-]+)", operand_str)
        defs[name] = (opcode, operand_names)
        pm = _PARAM_RE.match(line)
        if pm and in_entry:
            hlo_name, type_s, idx, shard = pm.groups()
            vals = _shape_dtype(type_s)
            dtype, shape = vals[0] if vals else ("f32", ())
            meta = _METADATA_RE.search(line)
            ep = HloEntryParam(
                index=int(idx), hlo_name=hlo_name, dtype=dtype,
                local_shape=shape, sharding=shard or "",
                arg_name=(meta.group(1) if meta else "") or "")
            ep.global_shape = _global_shape(ep.local_shape, ep.sharding)
            entries[ep.index] = ep
            continue
        base = opcode[:-len("-start")] if opcode.endswith("-start") \
            else opcode
        if base.endswith("-done"):
            continue
        if base not in _COLLECTIVE_OPS:
            continue
        meta_m = _METADATA_RE.search(line)
        meta = None
        if meta_m:
            meta = {"op_name": meta_m.group(1),
                    "source_file": meta_m.group(2),
                    "source_line": meta_m.group(3)}
        raw, ng, gs = _parse_groups(rest)
        ch = re.search(r"channel_id=(\d+)", rest)
        collectives.append(HloCollective(
            name=name, kind=base,
            out=_shape_dtype(type_str),
            operands=_shape_dtype(operand_str),
            operand_names=operand_names,
            replica_groups=raw, num_groups=ng, group_size=gs,
            channel_id=int(ch.group(1)) if ch else None,
            where=_where_of(meta)))
    return collectives, entries, defs


# ----------------------------------------------------------- inventory

def collective_inventory(text_or_parsed, executable: str = ""
                         ) -> List[dict]:
    """The static collective ledger: one row per collective instruction,
    in the EXACT row schema of trace_analysis.collective_rows() so the
    static and runtime tables diff cell for cell — timing columns are
    None (statics have no clock), `bytes` is computed from shapes.
    Extra keys (kind/dtype/shapes/replica_groups/where/group_size) ride
    along for the sharding passes and the CLI table."""
    colls = text_or_parsed[0] if isinstance(text_or_parsed, tuple) \
        else parse_hlo(text_or_parsed)[0]
    rows = []
    for c in colls:
        rows.append({
            "name": c.name, "calls": 1,
            "dur_us": None, "busy_us": None, "overlapped_us": None,
            "exposed_us": None, "exposed_frac": None,
            "bytes": c.bytes, "bus_gbps": None,
            # static-only columns
            "kind": c.kind,
            "dtype": ",".join(sorted({d for d, _ in c.out})),
            "shapes": [list(s) for _, s in c.out],
            "replica_groups": c.replica_groups,
            "group_size": c.group_size,
            "where": c.where,
        })
    rows.sort(key=lambda r: (-r["bytes"], r["name"]))
    return rows


# -------------------------------------------------------------- passes

def _walk_to_param(start_names: Sequence[str], defs, entries_by_name):
    """Follow pass-through ops from an instruction's operands back to an
    ENTRY parameter; returns the HloEntryParam or None. Unary fusions
    whose generated name is composed purely of pass-through op kinds
    ("convert_copy_fusion") count as pass-through — that is how a bf16
    parameter's f32 convert appears after fusion."""
    seen = set()
    stack = list(start_names)
    while stack:
        nm = stack.pop()
        if nm in seen:
            continue
        seen.add(nm)
        if nm in entries_by_name:
            return entries_by_name[nm]
        op, operands = defs.get(nm, (None, []))
        if op is None:
            continue
        passthrough = op in _PASSTHROUGH_OPS
        if not passthrough and op in ("fusion", "call") \
                and len(operands) == 1:
            head = nm.split(".")[0]
            words = [w for w in head.split("_")
                     if w not in ("fusion", "call")]
            passthrough = bool(words) and all(
                w in _PASSTHROUGH_FUSION_WORDS for w in words)
        if passthrough:
            stack.extend(operands)
    return None


def resharding_pass(parsed, executable: str = "",
                    param_names: Optional[Dict[str, str]] = None
                    ) -> List[Finding]:
    """Detect partitioner-inserted resharding of PARAMETERS: an
    all-gather whose input chain reaches a sharded entry parameter
    (certain), or whose operand/output shapes are exactly a sharded
    parameter's local/global shapes (strong shape evidence — the gather
    happens behind a multi-operand fusion). Either way the declared
    sharding is being undone every step: a wrong pspec on that layer, or
    an annotation the consuming op cannot honor.

    `param_names` maps lowering arg keypaths ("param_arrays[3]") to
    model-level names ("gpt.h.0.attn.qkv.weight") so the finding names
    the offending LAYER, not a flat index."""
    colls, entries, defs = parsed
    entries_by_name = {e.hlo_name: e for e in entries.values()}
    names = param_names or {}

    def disp(ep: HloEntryParam) -> str:
        return names.get(ep.arg_name) or ep.arg_name \
            or f"arg[{ep.index}]"

    out: List[Finding] = []
    for c in colls:
        if c.kind != "all-gather":
            continue
        hit = _walk_to_param(c.operand_names, defs, entries_by_name)
        certain = hit is not None and hit.sharded
        cands: List[HloEntryParam] = []
        if certain:
            cands = [hit]
        else:
            for ep in entries.values():
                if not ep.sharded or ep.global_shape is None:
                    continue
                if len(ep.local_shape) < 2:
                    continue
                if any(s == ep.global_shape for _, s in c.out) and any(
                        s == ep.local_shape for _, s in c.operands):
                    cands.append(ep)
        if not cands:
            continue
        who = " | ".join(disp(e) for e in cands[:3])
        loc = f" @ {c.where}" if c.where else ""
        out.append(Finding(
            "sharding", "param_gather", "warn",
            f"{c.name} gathers sharded parameter {who} back to "
            f"replicated ({cands[0].dtype}"
            f"{list(cands[0].global_shape or ())}, "
            f"{c.bytes / 1e6:.2f} MB/step) — the declared sharding is "
            f"undone every step"
            + ("" if certain else " (shape-matched through a fusion)"),
            where=f"{who}{loc}", executable=executable,
            data={"op": c.name, "params": [disp(e) for e in cands],
                  "bytes": c.bytes, "certain": certain,
                  "replica_groups": c.replica_groups}))
    return out


def replicated_pass(parsed, executable: str = "",
                    min_bytes: int = 1 << 20,
                    param_names: Optional[Dict[str, str]] = None,
                    mesh_axes: Optional[Dict[str, int]] = None
                    ) -> List[Finding]:
    """Flag large REPLICATED parameters in an otherwise-tensor-sharded
    executable — every device holds the full copy while its neighbors'
    parameters are sharded (the forgotten-pspec case: one 6.7B embedding
    left replicated silently costs a full HBM copy per chip). Quiet on
    purely data-parallel executables (replicated params are the design
    there): fires only when at least one floating ndim>=2 parameter IS
    sharded. With `param_names` (the TrainStep path) only mapped args
    count as parameters on BOTH sides — a dp-sharded float batch is not
    sharding evidence and a replicated batch is not a finding; without
    the mapping every floating ndim>=2 arg is treated as a parameter
    (the generic-callable approximation). The suggested pspec shards the
    largest divisible dim over the largest fitting mesh axis."""
    _, entries, _ = parsed
    names = param_names or {}
    floatish = {"f32", "f64", "f16", "bf16"}
    considered = [e for e in entries.values()
                  if not names or e.arg_name in names]
    sharded_weights = [e for e in considered
                       if e.sharded and e.dtype in floatish
                       and len(e.local_shape) >= 2]
    if not sharded_weights:
        return []
    out: List[Finding] = []
    for ep in considered:
        if ep.sharded or ep.dtype not in floatish \
                or len(ep.local_shape) < 1:
            continue
        nb = ep.local_bytes
        if nb < min_bytes:
            continue
        who = names.get(ep.arg_name) or ep.arg_name or f"arg[{ep.index}]"
        spec = None
        if mesh_axes:
            for dim in sorted(range(len(ep.local_shape)),
                              key=lambda i: -ep.local_shape[i]):
                fits = [a for a, s in mesh_axes.items()
                        if s > 1 and ep.local_shape[dim] % s == 0]
                if fits:
                    ax = max(fits, key=lambda a: mesh_axes[a])
                    spec = ["None"] * len(ep.local_shape)
                    spec[dim] = repr(ax)
                    spec = f"P({', '.join(spec)})"
                    break
        out.append(Finding(
            "sharding", "replicated_param", "warn",
            f"parameter {who} ({ep.dtype}{list(ep.local_shape)}, "
            f"{nb / 1e6:.2f} MB) is replicated on every device while "
            f"other parameters are sharded"
            + (f" — pspec {spec} would shard it" if spec else ""),
            where=who, executable=executable,
            data={"param": who, "bytes": nb,
                  "shape": list(ep.local_shape), "dtype": ep.dtype,
                  **({"suggested_pspec": spec} if spec else {})}))
    return out


# ---------------------------------------------------------------- audit

@dataclass
class ShardingAudit:
    """Everything the sharded passes proved about one compiled
    executable: the static collective ledger (`rows`), the structured
    `findings` (sharding + comm_plan passes, allowlist applied by the
    GraphLint caller), and the entry-parameter sharding table."""
    executable: str
    rows: List[dict]
    findings: Findings
    params: List[dict] = field(default_factory=list)
    plan: Optional[CommPlan] = None

    def by_kind(self) -> Dict[str, dict]:
        return rows_by_kind(self.rows)

    def table(self, top: int = 20) -> str:
        """The static ledger in the ONE collective-row format (shared
        with the runtime DistributedView/CollectiveLedger renderers)."""
        from ..profiler.trace_analysis import format_collective_rows
        lines = [f"---- Static collective inventory ({self.executable}) "
                 f"----"]
        if not self.rows:
            lines.append("no collectives in the lowered module "
                         "(single-shard program)")
            return "\n".join(lines)
        lines += format_collective_rows(self.rows, top=top)
        agg = self.by_kind()
        lines.append("per kind: " + ", ".join(
            f"{k} x{v['calls']} ({(v['bytes'] or 0) / 1e6:.2f} MB)"
            for k, v in sorted(agg.items())))
        return "\n".join(lines)

    def to_dict(self) -> dict:
        return {"executable": self.executable,
                "rows": [dict(r) for r in self.rows],
                "by_kind": {k: {kk: vv for kk, vv in v.items()
                                if kk != "names"}
                            for k, v in self.by_kind().items()},
                "findings": self.findings.to_dicts(),
                "params": list(self.params),
                "plan": repr(self.plan) if self.plan else None}


def audit_hlo(text: str, executable: str = "",
              param_names: Optional[Dict[str, str]] = None,
              plan: Optional[CommPlan] = None,
              replicated_bytes: int = 1 << 20,
              mesh_axes: Optional[Dict[str, int]] = None
              ) -> ShardingAudit:
    """Run every sharding pass over one optimized-HLO module text."""
    parsed = parse_hlo(text)
    rows = collective_inventory(parsed, executable)
    findings = Findings()
    findings.extend(resharding_pass(parsed, executable,
                                    param_names=param_names))
    findings.extend(replicated_pass(parsed, executable,
                                    min_bytes=replicated_bytes,
                                    param_names=param_names,
                                    mesh_axes=mesh_axes))
    if plan is not None:
        findings.extend(plan.check(rows, executable=executable))
    names = param_names or {}
    params = [{"index": e.index,
               "name": names.get(e.arg_name) or e.arg_name,
               "dtype": e.dtype, "local_shape": list(e.local_shape),
               "global_shape": list(e.global_shape or ()),
               "sharded": e.sharded, "sharding": e.sharding}
              for _, e in sorted(parsed[1].items())]
    return ShardingAudit(executable=executable, rows=rows,
                         findings=findings, params=params, plan=plan)


def compiled_hlo_text(fn, *args, **kwargs) -> str:
    """Optimized (post-SPMD-partitioning) HLO of a jitted callable for
    abstract args — lower + compile, nothing executes. The collectives
    only exist AFTER partitioning, so `lowered.as_text()` (StableHLO,
    annotations only) is not enough."""
    lowered = fn.lower(*args, **kwargs)
    return lowered.compile().as_text()


# ------------------------------------------------- static-vs-runtime diff

def diff_ledgers(static_rows: Sequence[dict], runtime_rows: Sequence[dict],
                 steps: Optional[int] = None, rtol: float = 0.01
                 ) -> List[dict]:
    """Diff the static inventory against a runtime trace ledger, by
    collective kind (instruction names differ between an HLO text and a
    trace capture; the kind aggregation is the stable join key). Runtime
    bytes/calls are divided by `steps` to get per-step figures; static
    rows are already per-step. Returns one dict per kind:
    {kind, static_bytes, runtime_bytes, static_calls, runtime_calls,
    rel_err, ok} — rel_err is None (and ok False) when one side is
    missing or carries no bytes."""
    div = max(steps or 1, 1)
    s = rows_by_kind(static_rows)
    r = rows_by_kind(runtime_rows)
    out = []
    for kind in sorted(set(s) | set(r)):
        sb = s.get(kind, {}).get("bytes")
        rb = r.get(kind, {}).get("bytes")
        rb_step = rb / div if rb is not None else None
        rel = None
        if sb is not None and rb_step:
            rel = abs(sb - rb_step) / rb_step
        ok = rel is not None and rel <= rtol
        out.append({"kind": kind,
                    "static_bytes": sb,
                    "runtime_bytes": rb_step,
                    "static_calls": s.get(kind, {}).get("calls", 0),
                    "runtime_calls": (r.get(kind, {}).get("calls", 0)
                                      / div),
                    "rel_err": rel, "ok": ok})
    return out
