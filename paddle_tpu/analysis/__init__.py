"""paddle_tpu.analysis — static analysis over jaxprs and lowered HLO.

Proves the framework's serving/training invariants at BUILD time instead
of detecting their violation at runtime:

  zero host syncs     host_transfer pass + transfer_guard()
  donation honored    donation pass (input_output_alias cross-check)
  bf16 stays bf16     dtype_promotion pass (+ documented f32 allowlist)
  no baked constants  baked_const pass (closure-captured HBM duplication)
  zero recompiles     recompile module (abstract signature differ — the
                      ServingEngine pre-flight reject)

  sharding proven      sharding module (ISSUE 15): the post-SPMD HLO's
                       collective inventory (static twin of the runtime
                       trace ledger), partitioner-inserted-resharding and
                       large-replicated-parameter passes, and the
                       CommPlan declared-communication check — all
                       before a single chip runs the program

Entry points: GraphLint.check(fn, *args) for one executable,
GraphLint.check_sharded(...) for an executable lowered under a mesh,
lint_capture()+check_calls for the framework's own serving executables,
jit.TrainStep(lint=...) / inference.ServingConfig(lint=...) opt-ins, and
the tools/graph_lint.py CLI over the standard model set (including the
train-step-dp / train-step-tp sharded targets and the comm-xcheck
static-vs-runtime bytes cross-check).
"""
from .findings import (Allowlist, ConfigValidationError,  # noqa: F401
                       DEFAULT_ALLOWLIST, Finding, Findings,
                       GraphLintError)
from .passes import (baked_const_pass, donation_pass,  # noqa: F401
                     dtype_promotion_pass, host_transfer_pass,
                     parse_io_aliases)
from .recompile import (abstract_signature, diff_signatures,  # noqa: F401
                        explain_recompile)
from .transfer import (HostTransferError, current_layer_path,  # noqa: F401
                       transfer_guard)
from .commplan import (CommPlan, CommPlanError,  # noqa: F401
                       collective_kind, rows_by_kind, serving_comm_plan,
                       train_comm_plan)
from .sharding import (ShardingAudit, audit_hlo,  # noqa: F401
                       collective_inventory, compiled_hlo_text,
                       diff_ledgers, replicated_pass, resharding_pass)
from .lint import ALL_PASSES, GraphLint, lint_capture  # noqa: F401
