"""Recompile-hazard lint — statically diff two abstract call signatures
and explain which argument will force a recompile.

jax.jit keys its executable cache on: the pytree STRUCTURE of the
arguments, each leaf's (shape, dtype, weak_type), and the values of
static arguments. Any delta in that key is a retrace + XLA compile —
the r7 StepMonitor detects this at runtime (the executable already
built); this module makes the same judgment BEFORE tracing, so a
serving frontend can refuse a request (or a pre-flight check can fail
a job) while the explanation still names the offending leaf.

    sig = abstract_signature(ids, lens)         # what the executable keys on
    findings = diff_signatures(sig, abstract_signature(ids2, lens))
    explain_recompile(sig_a, sig_b)             # one human string
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np
import jax

from .findings import Finding, Findings


def _sharding_key(a) -> str:
    """The sharding component of a leaf's cache key. Only a
    NamedSharding participates (spec + mesh axis sizes — the same spec
    on a different mesh shape is a different partition). Everything
    else — host arrays, uncommitted and single-device leaves —
    normalizes to "": moving a host batch onto the default device never
    recompiled, and the signature must not claim it does. (Committed
    non-default single-device placements DO recompile but are
    indistinguishable from the default here without risking false
    rejects on plain host batches; the runtime recompile detector still
    catches that case.)"""
    s = getattr(a, "sharding", None)
    if s is None:
        return ""
    spec = getattr(s, "spec", None)
    if spec is not None:
        mesh = getattr(s, "mesh", None)
        axes = ""
        try:
            axes = ",".join(f"{k}={v}" for k, v in dict(mesh.shape).items())
        except Exception:
            pass
        return f"NamedSharding({spec}, mesh[{axes}])"
    return ""


def _leaf_key(a) -> Tuple:
    """(shape, dtype, weak_type, sharding) for an array-like leaf; repr
    for a static (non-array) leaf — exactly the distinctions jit keys
    on. Sharding joined the key in ISSUE 15: two calls differing only by
    NamedSharding recompile (and the resharding moves bytes first), and
    the old signature reported "no difference" for them."""
    if hasattr(a, "shape") and hasattr(a, "dtype"):
        weak = bool(getattr(a, "weak_type", False)
                    or getattr(getattr(a, "aval", None), "weak_type",
                               False))
        return ("array", tuple(a.shape), str(np.dtype(a.dtype)), weak,
                _sharding_key(a))
    return ("static", repr(a))


def abstract_signature(*args, **kwargs):
    """The abstract cache key of a call: (treedef string, leaf keys).
    Accepts arrays, Tensors (unwrapped via ._data), ShapeDtypeStructs,
    numpy arrays, and static python values."""
    from ..core.tensor import Tensor

    def unwrap(x):
        return x._data if isinstance(x, Tensor) else x

    args = jax.tree.map(unwrap, args,
                        is_leaf=lambda x: isinstance(x, Tensor))
    kwargs = jax.tree.map(unwrap, kwargs,
                          is_leaf=lambda x: isinstance(x, Tensor))
    leaves, treedef = jax.tree.flatten((args, kwargs))
    return (str(treedef), tuple(_leaf_key(a) for a in leaves))


def diff_signatures(old, new, executable: str = "",
                    names: Optional[Sequence[str]] = None) -> Findings:
    """Findings for every component of the cache key that changed —
    each one names the leaf and the kind of delta (shape / dtype /
    weak_type / static value / structure) that will force a recompile."""
    out = Findings()
    old_tree, old_leaves = old
    new_tree, new_leaves = new
    if old_tree != new_tree:
        out.add(Finding(
            "recompile_hazard", "structure", "error",
            "argument pytree structure changed — different executable "
            "unconditionally", executable=executable,
            data={"old": old_tree, "new": new_tree}))
        return out
    if len(old_leaves) != len(new_leaves):
        out.add(Finding(
            "recompile_hazard", "structure", "error",
            f"leaf count changed ({len(old_leaves)} -> "
            f"{len(new_leaves)})", executable=executable))
        return out
    for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
        if o == n:
            continue
        name = names[i] if names and i < len(names) else f"leaf[{i}]"
        if o[0] != n[0]:
            out.add(Finding(
                "recompile_hazard", "structure", "error",
                f"{name} changed kind ({o[0]} -> {n[0]})",
                where=name, executable=executable))
            continue
        if o[0] == "static":
            out.add(Finding(
                "recompile_hazard", "static", "error",
                f"{name}: static value {o[1]} -> {n[1]} — static args "
                f"are baked into the executable",
                where=name, executable=executable))
            continue
        _, oshape, odt, oweak, oshard = o
        _, nshape, ndt, nweak, nshard = n
        if oshape != nshape:
            out.add(Finding(
                "recompile_hazard", "shape", "error",
                f"{name}: shape {list(oshape)} -> {list(nshape)} forces "
                f"a retrace + compile",
                where=name, executable=executable,
                data={"old": list(oshape), "new": list(nshape)}))
        if odt != ndt:
            out.add(Finding(
                "recompile_hazard", "dtype", "error",
                f"{name}: dtype {odt} -> {ndt} forces a retrace + "
                f"compile",
                where=name, executable=executable,
                data={"old": odt, "new": ndt}))
        if oweak != nweak:
            out.add(Finding(
                "recompile_hazard", "weak_type", "warn",
                f"{name}: weak_type {oweak} -> {nweak} — a python "
                f"scalar vs array input distinction recompiles even at "
                f"identical shape/dtype",
                where=name, executable=executable))
        if oshard != nshard:
            out.add(Finding(
                "recompile_hazard", "sharding", "error",
                f"{name}: sharding {oshard or '(unspecified)'} -> "
                f"{nshard or '(unspecified)'} — a resharded input "
                f"forces a retrace + compile (and the device_put "
                f"resharding moves the bytes first)",
                where=name, executable=executable,
                data={"old": oshard, "new": nshard}))
    return out


def explain_recompile(old, new, names: Optional[Sequence[str]] = None
                      ) -> str:
    """One human-readable line: why `new` cannot reuse `old`'s
    executable (empty string = it can — same cache key)."""
    fs = diff_signatures(old, new, names=names)
    return "; ".join(f.message for f in fs)
