"""CommPlan — declare the collectives an executable is ALLOWED to run,
and fail lint when the SPMD partitioner inserted anything else.

The sharding inventory (analysis.sharding) answers "which collectives did
the partitioner emit?"; this module answers "are those the ones we MEANT?"
A plan maps collective kinds to count specs:

    plan = CommPlan({"all-reduce": "+"})              # grad sync only
    plan = CommPlan({"all-reduce": 30,                # exact count
                     "all-gather": (1, 8)})           # bounded range
    plan.check(rows)                                  # -> Findings
    plan.verify(rows, executable="train_step")        # -> CommPlanError

Count specs: an int is exact, ``"+"`` means "present, any count",
``(lo, hi)`` is an inclusive range, ``0`` forbids the kind explicitly
(same as omitting it, but self-documenting). Kinds absent from the plan
are FORBIDDEN unless ``allow_other=True`` — the default-deny is the
point: an accidental resharding all-gather in a "one grad all-reduce per
layer, nothing in forward" step must fail loudly, not average into a
table nobody reads.

Rows are collective-ledger rows (analysis.sharding.collective_inventory
or profiler.trace_analysis.collective_rows — the plan checks the KIND
aggregation, so it accepts either side of the static/runtime pair).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .findings import Finding, Findings, GraphLintError

#: the HLO collective opcodes a plan can speak about
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

_SUFFIX_RE = re.compile(r"(-start|-done)?(\.\d+)?$")


def collective_kind(name: str) -> Optional[str]:
    """Base collective kind of an op name ("all-reduce.3" ->
    "all-reduce", "all-gather-start.1" -> "all-gather"); None for a
    non-collective name. The one normalization both the static
    inventory and the runtime trace ledger agree on — async -start/-done
    pairs collapse onto their kind (the -done row carries no new
    transfer)."""
    low = name.lower()
    base = _SUFFIX_RE.sub("", low)
    for k in COLLECTIVE_KINDS:
        if base == k or base.startswith(k):
            return k
    # fusion-wrapped names ("all-reduce-fusion") keep their kind
    for k in COLLECTIVE_KINDS:
        if k in low:
            return k
    return None


def rows_by_kind(rows: Sequence[dict]) -> Dict[str, dict]:
    """Aggregate ledger rows by collective kind: {kind: {"calls", "bytes",
    "names"}}. `bytes` is None when NO row of the kind carries bytes;
    "-done" rows are skipped (their "-start" twin carries the op)."""
    out: Dict[str, dict] = {}
    for r in rows:
        name = r.get("name", "")
        if "-done" in name:
            continue
        kind = collective_kind(name)
        if kind is None:
            continue
        g = out.setdefault(kind, {"calls": 0, "bytes": None, "names": []})
        g["calls"] += int(r.get("calls", 1))
        b = r.get("bytes")
        if b is not None:
            g["bytes"] = (g["bytes"] or 0) + int(b)
        g["names"].append(name)
    return out


CountSpec = Union[int, str, Tuple[int, int]]


class CommPlanError(GraphLintError):
    """The executable's collective inventory violates its CommPlan.
    Subclasses GraphLintError so existing `except GraphLintError`
    pre-flight callers catch plan violations too; `findings` carries the
    structured comm_plan rows (extra / missing / count)."""


class CommPlan:
    """Declared communication plan for one executable (module docstring
    has the spec grammar)."""

    def __init__(self, expect: Dict[str, CountSpec],
                 allow_other: bool = False):
        self.expect: Dict[str, CountSpec] = {}
        for kind, spec in (expect or {}).items():
            k = collective_kind(kind) or kind
            if k not in COLLECTIVE_KINDS:
                raise ValueError(
                    f"unknown collective kind {kind!r} "
                    f"(one of {COLLECTIVE_KINDS})")
            self._validate_spec(kind, spec)
            self.expect[k] = spec
        self.allow_other = allow_other

    @staticmethod
    def _validate_spec(kind, spec):
        if isinstance(spec, bool) or not (
                isinstance(spec, int)
                or spec == "+"
                or (isinstance(spec, (tuple, list)) and len(spec) == 2
                    and all(isinstance(x, int) for x in spec))):
            raise ValueError(
                f"bad count spec for {kind!r}: {spec!r} (int exact, "
                f"'+' present, (lo, hi) range, 0 forbidden)")

    def __repr__(self):
        other = ", other: allowed" if self.allow_other else ""
        return (f"CommPlan({{"
                + ", ".join(f"{k!r}: {v!r}"
                            for k, v in self.expect.items())
                + f"}}{other})")

    @staticmethod
    def _spec_ok(spec: CountSpec, count: int) -> bool:
        if spec == "+":
            return count >= 1
        if isinstance(spec, (tuple, list)):
            lo, hi = spec
            return lo <= count <= hi
        return count == int(spec)

    @staticmethod
    def _spec_str(spec: CountSpec) -> str:
        if spec == "+":
            return ">= 1"
        if isinstance(spec, (tuple, list)):
            return f"{spec[0]}..{spec[1]}"
        return str(spec)

    # ------------------------------------------------------------ check
    def check(self, rows: Sequence[dict], executable: str = "") -> Findings:
        """Findings for every way the inventory departs from the plan:

        comm_extra    a kind the plan forbids is present (the accidental
                      resharding case — the finding names the op names)
        comm_missing  a planned kind is absent (the grad sync you meant
                      to have did not lower — usually a mesh/pspec typo)
        comm_count    a planned kind is present at the wrong count
        """
        got = rows_by_kind(rows)
        out = Findings()
        for kind, g in got.items():
            spec = self.expect.get(kind)
            if spec is None or spec == 0:
                if self.allow_other and spec is None:
                    continue
                out.add(Finding(
                    "comm_plan", "comm_extra", "error",
                    f"{g['calls']} {kind} op(s) not in the comm plan "
                    f"({', '.join(g['names'][:4])}"
                    f"{', ...' if len(g['names']) > 4 else ''}) — "
                    f"partitioner-inserted communication the plan "
                    f"forbids",
                    where=kind, executable=executable,
                    data={"kind": kind, "calls": g["calls"],
                          "bytes": g["bytes"],
                          "names": g["names"][:16]}))
            elif not self._spec_ok(spec, g["calls"]):
                out.add(Finding(
                    "comm_plan", "comm_count", "error",
                    f"{kind}: {g['calls']} op(s), plan expects "
                    f"{self._spec_str(spec)}",
                    where=kind, executable=executable,
                    data={"kind": kind, "calls": g["calls"],
                          "expect": self._spec_str(spec)}))
        for kind, spec in self.expect.items():
            if kind in got:
                continue
            required = (spec == "+"
                        or (isinstance(spec, int) and spec > 0)
                        or (isinstance(spec, (tuple, list))
                            and spec[0] > 0))
            if not required:
                continue
            out.add(Finding(
                "comm_plan", "comm_missing", "error",
                f"{kind}: absent, plan expects "
                f"{self._spec_str(spec)} — the collective you planned "
                f"for never lowered (mesh axis missing or pspec "
                f"filtered away?)",
                where=kind, executable=executable,
                data={"kind": kind, "expect": self._spec_str(spec)}))
        return out

    def verify(self, rows: Sequence[dict], executable: str = ""):
        """Raise CommPlanError when `check` finds violations; returns the
        (empty) Findings otherwise."""
        fs = self.check(rows, executable=executable)
        if fs:
            raise CommPlanError(fs, executable)
        return fs


def serving_comm_plan(num_layers: Optional[int] = None) -> CommPlan:
    """THE declared multi-chip serving plan (ISSUE 16): a head-sharded
    paged engine's executables communicate through mp-group all-reduces
    and NOTHING else — exactly one per row-parallel matmul (attention
    out-projection + MLP down-projection), i.e. ``2 * num_layers`` per
    executable; weights ride replicated, the qkv projection head-shards
    with a free local slice, pool scatters/gathers are shard-local by
    construction, logits and sampling stay replicated.

    With ``num_layers`` the count is EXACT (the sharp form the
    graph_lint sharded-engine target gates on — a partitioner-inserted
    KV gather or resharded embedding shows up as comm_extra/comm_count
    and is named down to the op); without it the plan still default-
    denies every non-all-reduce kind."""
    if num_layers is None:
        return CommPlan({"all-reduce": "+"})
    return CommPlan({"all-reduce": 2 * int(num_layers)})
