"""CommPlan — declare the collectives an executable is ALLOWED to run,
and fail lint when the SPMD partitioner inserted anything else.

The sharding inventory (analysis.sharding) answers "which collectives did
the partitioner emit?"; this module answers "are those the ones we MEANT?"
A plan maps collective kinds to count specs:

    plan = CommPlan({"all-reduce": "+"})              # grad sync only
    plan = CommPlan({"all-reduce": 30,                # exact count
                     "all-gather": (1, 8)})           # bounded range
    plan.check(rows)                                  # -> Findings
    plan.verify(rows, executable="train_step")        # -> CommPlanError

Count specs: an int is exact, ``"+"`` means "present, any count",
``(lo, hi)`` is an inclusive range, ``0`` forbids the kind explicitly
(same as omitting it, but self-documenting). Kinds absent from the plan
are FORBIDDEN unless ``allow_other=True`` — the default-deny is the
point: an accidental resharding all-gather in a "one grad all-reduce per
layer, nothing in forward" step must fail loudly, not average into a
table nobody reads.

DTYPE-QUALIFIED specs (ISSUE 20): a key may pin the wire dtype —
``"all-reduce:s8"`` matches only s8 all-reduces; rows whose dtype has no
qualified key fall back to the bare-kind spec, and when only qualified
keys exist for a kind the unmatched dtype is comm_extra. A spec may also
be a dict ``{"calls": CountSpec, "max_bytes": int}`` — comm_bytes fires
when the matched rows' summed bytes exceed the cap. Together these give
the quantized-gradient default-deny: ``train_comm_plan(dtype="int8")``
requires the s8 gradient all-reduces AND forbids any f32 all-reduce
bigger than the scale/loss side-channel — an f32 gradient sync sneaking
back (a fallback-classifier regression, a shard_map bypass) fails as
comm_bytes, not as a byte row nobody reads. Dtype qualification needs
rows that CARRY dtype (the static inventory does; runtime trace rows do
not — check those against bare-kind plans).

Rows are collective-ledger rows (analysis.sharding.collective_inventory
or profiler.trace_analysis.collective_rows — the plan checks the KIND
aggregation, so it accepts either side of the static/runtime pair).
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

from .findings import Finding, Findings, GraphLintError

#: the HLO collective opcodes a plan can speak about
COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute",
                    "collective-broadcast")

_SUFFIX_RE = re.compile(r"(-start|-done)?(\.\d+)?$")


def collective_kind(name: str) -> Optional[str]:
    """Base collective kind of an op name ("all-reduce.3" ->
    "all-reduce", "all-gather-start.1" -> "all-gather"); None for a
    non-collective name. The one normalization both the static
    inventory and the runtime trace ledger agree on — async -start/-done
    pairs collapse onto their kind (the -done row carries no new
    transfer)."""
    low = name.lower()
    base = _SUFFIX_RE.sub("", low)
    for k in COLLECTIVE_KINDS:
        if base == k or base.startswith(k):
            return k
    # fusion-wrapped names ("all-reduce-fusion") keep their kind
    for k in COLLECTIVE_KINDS:
        if k in low:
            return k
    return None


def rows_by_kind(rows: Sequence[dict],
                 by_dtype: bool = False) -> Dict[str, dict]:
    """Aggregate ledger rows by collective kind: {kind: {"calls", "bytes",
    "names"}}. `bytes` is None when NO row of the kind carries bytes;
    "-done" rows are skipped (their "-start" twin carries the op).
    With ``by_dtype`` the key is ``"kind:dtype"`` for rows that carry a
    dtype column (the static inventory) and the bare kind otherwise —
    the aggregation dtype-qualified CommPlan specs check against; each
    group additionally records its "kind" and "dtype"."""
    out: Dict[str, dict] = {}
    for r in rows:
        name = r.get("name", "")
        if "-done" in name:
            continue
        kind = collective_kind(name)
        if kind is None:
            continue
        dtype = r.get("dtype") if by_dtype else None
        key = f"{kind}:{dtype}" if dtype else kind
        g = out.setdefault(key, {"calls": 0, "bytes": None, "names": [],
                                 "kind": kind, "dtype": dtype})
        g["calls"] += int(r.get("calls", 1))
        b = r.get("bytes")
        if b is not None:
            g["bytes"] = (g["bytes"] or 0) + int(b)
        g["names"].append(name)
    return out


CountSpec = Union[int, str, Tuple[int, int]]


class CommPlanError(GraphLintError):
    """The executable's collective inventory violates its CommPlan.
    Subclasses GraphLintError so existing `except GraphLintError`
    pre-flight callers catch plan violations too; `findings` carries the
    structured comm_plan rows (extra / missing / count)."""


class CommPlan:
    """Declared communication plan for one executable (module docstring
    has the spec grammar)."""

    def __init__(self, expect: Dict[str, CountSpec],
                 allow_other: bool = False):
        self.expect: Dict[str, CountSpec] = {}
        for key, spec in (expect or {}).items():
            kind, _, dtype = str(key).partition(":")
            k = collective_kind(kind) or kind
            if k not in COLLECTIVE_KINDS:
                raise ValueError(
                    f"unknown collective kind {kind!r} "
                    f"(one of {COLLECTIVE_KINDS})")
            self._validate_spec(key, spec)
            self.expect[f"{k}:{dtype}" if dtype else k] = spec
        self.allow_other = allow_other

    @staticmethod
    def _split_spec(spec):
        """(CountSpec, max_bytes) of a plain or dict spec."""
        if isinstance(spec, dict):
            return spec.get("calls", "+"), spec.get("max_bytes")
        return spec, None

    @classmethod
    def _validate_spec(cls, kind, spec):
        if isinstance(spec, dict):
            extra = set(spec) - {"calls", "max_bytes"}
            if extra:
                raise ValueError(
                    f"bad spec for {kind!r}: unknown dict keys {extra} "
                    "(allowed: calls, max_bytes)")
            mb = spec.get("max_bytes")
            if mb is not None and (isinstance(mb, bool)
                                   or not isinstance(mb, int) or mb < 0):
                raise ValueError(
                    f"bad max_bytes for {kind!r}: {mb!r}")
            spec = spec.get("calls", "+")
        if isinstance(spec, bool) or not (
                isinstance(spec, int)
                or spec == "+"
                or (isinstance(spec, (tuple, list)) and len(spec) == 2
                    and all(isinstance(x, int) for x in spec))):
            raise ValueError(
                f"bad count spec for {kind!r}: {spec!r} (int exact, "
                f"'+' present, (lo, hi) range, 0 forbidden, or "
                "{'calls': ..., 'max_bytes': ...})")

    def __repr__(self):
        other = ", other: allowed" if self.allow_other else ""
        return (f"CommPlan({{"
                + ", ".join(f"{k!r}: {v!r}"
                            for k, v in self.expect.items())
                + f"}}{other})")

    @staticmethod
    def _spec_ok(spec: CountSpec, count: int) -> bool:
        if spec == "+":
            return count >= 1
        if isinstance(spec, (tuple, list)):
            lo, hi = spec
            return lo <= count <= hi
        return count == int(spec)

    @staticmethod
    def _spec_str(spec: CountSpec) -> str:
        if spec == "+":
            return ">= 1"
        if isinstance(spec, (tuple, list)):
            return f"{spec[0]}..{spec[1]}"
        return str(spec)

    # ------------------------------------------------------------ check
    def check(self, rows: Sequence[dict], executable: str = "") -> Findings:
        """Findings for every way the inventory departs from the plan:

        comm_extra    a kind (or kind:dtype) the plan forbids is present
                      (the accidental resharding case — the finding names
                      the op names)
        comm_missing  a planned kind is absent (the grad sync you meant
                      to have did not lower — usually a mesh/pspec typo)
        comm_count    a planned kind is present at the wrong count
        comm_bytes    a planned kind's summed bytes exceed its max_bytes
                      cap (the quantized-sync default-deny: a big f32
                      gradient all-reduce under an int8 plan)
        """
        has_dtype_keys = any(":" in k for k in self.expect)
        got = rows_by_kind(rows, by_dtype=has_dtype_keys)
        out = Findings()
        # resolve each row group onto a spec key (exact kind:dtype first,
        # bare kind fallback), then judge counts/bytes PER SPEC KEY — a
        # bare "all-reduce" spec pools every dtype, qualified keys split
        matched: Dict[str, dict] = {}
        for key, g in got.items():
            kind = g.get("kind") or key
            spec_key = key if key in self.expect else (
                kind if kind in self.expect else None)
            if spec_key is None or self._split_spec(
                    self.expect.get(spec_key, 0))[0] == 0:
                if self.allow_other and spec_key is None:
                    continue
                out.add(Finding(
                    "comm_plan", "comm_extra", "error",
                    f"{g['calls']} {key} op(s) not in the comm plan "
                    f"({', '.join(g['names'][:4])}"
                    f"{', ...' if len(g['names']) > 4 else ''}) — "
                    f"partitioner-inserted communication the plan "
                    f"forbids",
                    where=key, executable=executable,
                    data={"kind": kind, "dtype": g.get("dtype"),
                          "calls": g["calls"], "bytes": g["bytes"],
                          "names": g["names"][:16]}))
                continue
            m = matched.setdefault(spec_key, {"calls": 0, "bytes": None,
                                              "names": []})
            m["calls"] += g["calls"]
            if g["bytes"] is not None:
                m["bytes"] = (m["bytes"] or 0) + g["bytes"]
            m["names"] += g["names"]
        for spec_key, m in matched.items():
            cspec, max_bytes = self._split_spec(self.expect[spec_key])
            if not self._spec_ok(cspec, m["calls"]):
                out.add(Finding(
                    "comm_plan", "comm_count", "error",
                    f"{spec_key}: {m['calls']} op(s), plan expects "
                    f"{self._spec_str(cspec)}",
                    where=spec_key, executable=executable,
                    data={"kind": spec_key, "calls": m["calls"],
                          "expect": self._spec_str(cspec)}))
            if max_bytes is not None and m["bytes"] is not None \
                    and m["bytes"] > max_bytes:
                out.add(Finding(
                    "comm_plan", "comm_bytes", "error",
                    f"{spec_key}: {m['bytes']} bytes exceed the plan's "
                    f"{max_bytes}-byte cap "
                    f"({', '.join(m['names'][:4])}"
                    f"{', ...' if len(m['names']) > 4 else ''}) — "
                    f"oversized communication on a lane the plan only "
                    f"allows as a side-channel",
                    where=spec_key, executable=executable,
                    data={"kind": spec_key, "bytes": m["bytes"],
                          "max_bytes": max_bytes,
                          "names": m["names"][:16]}))
        for spec_key, spec in self.expect.items():
            if spec_key in matched:
                continue
            cspec, _ = self._split_spec(spec)
            required = (cspec == "+"
                        or (isinstance(cspec, int) and cspec > 0)
                        or (isinstance(cspec, (tuple, list))
                            and cspec[0] > 0))
            if not required:
                continue
            out.add(Finding(
                "comm_plan", "comm_missing", "error",
                f"{spec_key}: absent, plan expects "
                f"{self._spec_str(cspec)} — the collective you planned "
                f"for never lowered (mesh axis missing or pspec "
                f"filtered away?)",
                where=spec_key, executable=executable,
                data={"kind": spec_key, "expect": self._spec_str(cspec)}))
        return out

    def verify(self, rows: Sequence[dict], executable: str = ""):
        """Raise CommPlanError when `check` finds violations; returns the
        (empty) Findings otherwise."""
        fs = self.check(rows, executable=executable)
        if fs:
            raise CommPlanError(fs, executable)
        return fs


def serving_comm_plan(num_layers: Optional[int] = None) -> CommPlan:
    """THE declared multi-chip serving plan (ISSUE 16): a head-sharded
    paged engine's executables communicate through mp-group all-reduces
    and NOTHING else — exactly one per row-parallel matmul (attention
    out-projection + MLP down-projection), i.e. ``2 * num_layers`` per
    executable; weights ride replicated, the qkv projection head-shards
    with a free local slice, pool scatters/gathers are shard-local by
    construction, logits and sampling stay replicated.

    With ``num_layers`` the count is EXACT (the sharp form the
    graph_lint sharded-engine target gates on — a partitioner-inserted
    KV gather or resharded embedding shows up as comm_extra/comm_count
    and is named down to the op); without it the plan still default-
    denies every non-all-reduce kind."""
    if num_layers is None:
        return CommPlan({"all-reduce": "+"})
    return CommPlan({"all-reduce": 2 * int(num_layers)})


def train_comm_plan(n_groups: Optional[int] = None, dtype: str = "f32",
                    max_f32_bytes: int = 1 << 20) -> CommPlan:
    """THE declared data-parallel training plan (ISSUE 20): gradient sync
    all-reduces and nothing else.

    ``dtype="f32"`` (or None) is the classic plan — all-reduce present,
    every other kind default-denied (the PR 14 regression class: a
    partitioner-inserted batch all-gather in the dp step must fail).

    ``dtype="int8"`` is the quantized plan for
    ``TrainStep(grad_comm="int8")``: the s8 gradient all-reduces must be
    present — ``n_groups`` (the ``_grad_groups`` layer-bucket count)
    bounds them as a RANGE, because XLA's all-reduce combiner may merge
    same-dtype neighbours — while f32 all-reduces are allowed only as the
    side-channel (per-chunk scale pmax, loss/stats pmean, the 0/1-d
    fallback groups) under ``max_f32_bytes``: an f32 GRADIENT all-reduce
    sneaking back in blows the cap and fails as comm_bytes. Size the cap
    at roughly an eighth of the f32 twin's all-reduce bytes (the default
    1 MiB suits toy/CI models; real models pass their own)."""
    if dtype in (None, "f32", "float32"):
        return CommPlan({"all-reduce": "+"})
    if dtype not in ("int8", "s8"):
        raise ValueError(f"train_comm_plan dtype={dtype!r}: expected "
                         "'f32' or 'int8'")
    n_f32 = 2 * int(n_groups) + 2 if n_groups else 4096
    return CommPlan({
        "all-reduce:s8": (1, int(n_groups)) if n_groups else "+",
        "all-reduce:f32": {"calls": (0, n_f32),
                           "max_bytes": int(max_f32_bytes)},
    })
