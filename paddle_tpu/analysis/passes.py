"""The jaxpr/HLO passes — each proves (or refutes) one framework invariant
at BUILD time, before the executable ever runs.

  host_transfer_pass    r8's "zero per-step host syncs": no callback /
                        infeed-outfeed primitive anywhere in the graph
                        (each one is a device->host round trip per step).
  dtype_promotion_pass  bf16 paths stay bf16: find convert_element_type
                        eqns that widen a LARGE low-precision tensor to
                        f32/f64 (weak-type promotions and stray astypes
                        both lower to exactly this op), with an allowlist
                        for deliberate f32 accumulations.
  baked_const_pass      no per-executable HBM duplication: closure-captured
                        arrays above a threshold that became jaxpr consts
                        get re-uploaded with EVERY executable that baked
                        them (the cached dense-twin/bench hazard).
  donation_pass         r9/r10's in-place KV updates: cross-check the
                        jit-level donated_invars against the lowered
                        module's input_output_alias table (donated but
                        unaliased = a silent copy every call) and flag
                        large non-donated inputs with a same-shape output
                        that COULD be donated.

All passes walk the jaxpr recursively (scan/cond/pjit/remat bodies
included) so an invariant can't hide inside a control-flow sub-jaxpr —
the decode loop IS a lax.scan body.
"""
from __future__ import annotations

import re
import warnings
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax

from .findings import Finding

# primitives that force a device->host (or host->device) transfer per
# execution — any of these inside a steady-state executable breaks the
# zero-sync invariant
CALLBACK_PRIMITIVES = {
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
}
# low-precision sources and wide targets for the promotion pass
_NARROW = {"bfloat16", "float16"}
_WIDE = {"float32", "float64"}


def _source_summary(eqn, max_frames: int = 4) -> str:
    """Caller chain 'file.py:123 (fn) < file.py:88 (caller) < ...' for an
    eqn, innermost first — naming the chain (not just the innermost frame)
    is what lets an allowlist entry match on the MEANINGFUL function
    (layer_norm, attention_reference, decode_static) instead of a lambda
    or closure body three frames down."""
    try:
        from jax._src import source_info_util
        frames = []
        for fr in source_info_util.user_frames(eqn.source_info):
            frames.append(f"{fr.file_name.rsplit('/', 1)[-1]}:"
                          f"{fr.start_line} ({fr.function_name})")
            if len(frames) >= max_frames:
                break
        if frames:
            return " < ".join(frames)
        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""


def iter_eqns(jaxpr) -> Iterable:
    """Yield every eqn in a (possibly Closed) jaxpr, descending into
    sub-jaxprs carried in eqn params (scan/while/cond/pjit/remat/custom
    vjp bodies)."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jx.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_eqns(sub)


def _sub_jaxprs(v):
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _sub_jaxprs(x)


def iter_consts(jaxpr) -> Iterable:
    """Yield every const array in a closed jaxpr tree (top-level consts
    plus consts of closed sub-jaxprs, e.g. a pjit body's)."""
    consts = getattr(jaxpr, "consts", None)
    if consts:
        yield from consts
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jx.eqns:
        for v in eqn.params.values():
            for sub in _sub_jaxprs(v):
                yield from iter_consts(sub)


def _nbytes(aval) -> int:
    try:
        return int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
    except Exception:
        return 0


# --------------------------------------------------------------- passes

def host_transfer_pass(closed_jaxpr, executable: str = "") -> List[Finding]:
    """Flag ops that force device<->host transfers inside the graph."""
    out = []
    for eqn in iter_eqns(closed_jaxpr):
        name = eqn.primitive.name
        if name in CALLBACK_PRIMITIVES:
            sev = "warn" if name == "debug_callback" else "error"
            out.append(Finding(
                "host_transfer", name, sev,
                f"`{name}` forces a device<->host round trip every "
                f"execution (zero-sync invariant)",
                where=_source_summary(eqn), executable=executable))
    return out


def dtype_promotion_pass(closed_jaxpr, executable: str = "",
                         min_bytes: int = 1 << 16) -> List[Finding]:
    """Flag convert_element_type eqns widening a large narrow-precision
    tensor to f32/f64 — the lowered form of BOTH stray `astype` calls and
    weak-type promotions (jnp inserts this op for every implicit widen).
    min_bytes is the WIDENED size: small scalars/rows (loss, stats,
    positions) are free; a [B,S,H] activation or [B,V] logits copy in f32
    doubles its HBM + bandwidth."""
    out = []
    for eqn in iter_eqns(closed_jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        try:
            src = eqn.invars[0].aval
            dst = eqn.outvars[0].aval
        except Exception:
            continue
        if str(src.dtype) in _NARROW and str(dst.dtype) in _WIDE:
            wide = _nbytes(dst)
            if wide >= min_bytes:
                out.append(Finding(
                    "dtype_promotion", f"{src.dtype}_to_{dst.dtype}",
                    "warn",
                    f"{src.dtype}{list(src.shape)} widened to {dst.dtype} "
                    f"({wide / 1e6:.2f} MB) — unintended f32 upcast in a "
                    f"low-precision path?",
                    where=_source_summary(eqn), executable=executable,
                    data={"shape": list(src.shape), "from": str(src.dtype),
                          "to": str(dst.dtype), "bytes": wide}))
    return out


def baked_const_pass(closed_jaxpr, executable: str = "",
                     min_bytes: int = 1 << 20) -> List[Finding]:
    """Flag large arrays baked into the jaxpr as consts. A const is
    closure-captured data: it is embedded per-executable (re-uploaded and
    held in HBM once per compiled program that captured it), invisible to
    donation, and silently stale if the Python-side array changes."""
    out = []
    for c in iter_consts(closed_jaxpr):
        shape = getattr(c, "shape", None)
        dtype = getattr(c, "dtype", None)
        if shape is None or dtype is None:
            continue
        nb = int(np.prod(shape)) * np.dtype(dtype).itemsize if shape else 0
        if nb >= min_bytes:
            out.append(Finding(
                "baked_const", "large_const", "warn",
                f"closure-captured {dtype}{list(shape)} "
                f"({nb / 1e6:.2f} MB) baked into the jaxpr as a const — "
                f"pass it as an argument (per-executable HBM duplication)",
                executable=executable,
                data={"shape": list(shape), "dtype": str(dtype),
                      "bytes": nb}))
    return out


# ------------------------------------------------------------- donation

_ALIAS_RE = re.compile(r"tf\.aliasing_output\s*=\s*(\d+)")


def parse_io_aliases(lowered_text: str) -> Tuple[int, dict]:
    """(n_args, {flat_arg_index: output_index}) from the lowered StableHLO
    module's @main signature — the compiled input_output_alias table as
    jax records it (`tf.aliasing_output` arg attributes).

    Parsing splits the signature at `%argN:` boundaries rather than
    matching the attribute dict with a brace regex: attr VALUES contain
    nested braces (`mhlo.sharding = "{replicated}"` sorts before
    tf.aliasing_output), and a `\\{[^}]*\\}` capture would truncate at
    the first inner `}` and silently drop the alias marker for every
    sharded executable."""
    m = re.search(r"func\.func\s+public\s+@main\s*\((.*?)\)\s*->",
                  lowered_text, re.S)
    if not m:
        return 0, {}
    # parts = [prefix, idx0, seg0, idx1, seg1, ...]: each seg holds that
    # argument's type + full attribute dict, up to the next %arg
    parts = re.split(r"%arg(\d+):", m.group(1))
    aliases = {}
    n = 0
    for i in range(1, len(parts) - 1, 2):
        idx = int(parts[i])
        n = max(n, idx + 1)
        al = _ALIAS_RE.search(parts[i + 1])
        if al:
            aliases[idx] = int(al.group(1))
    return n, aliases


def parse_compiled_aliases(compiled_text: str) -> dict:
    """{entry_param_index: output_tuple_index} from a compiled HloModule
    header's ``input_output_alias={ {out}: (param, {}, may-alias), ...}``
    table. Under SPMD partitioning (num_partitions > 1) jax defers
    donation aliasing to XLA: the lowered StableHLO carries NO
    tf.aliasing_output attributes and the alias table only exists after
    compile — reading the pre-compile text alone would misreport every
    sharded executable's donation as a silent copy."""
    m = re.search(r"input_output_alias=\{((?:[^{}]|\{[^{}]*\})*)\}",
                  compiled_text)
    if not m:
        return {}
    out = {}
    for om, pm in re.findall(r"\{(\d+)[^{}]*\}:\s*\((\d+)", m.group(1)):
        out[int(pm)] = int(om)
    return out


def donation_pass(fn, args, donate_argnums: Sequence[int] = (),
                  executable: str = "", min_bytes: int = 1 << 20,
                  closed_jaxpr=None, kwargs=None) -> List[Finding]:
    """Cross-check donation intent against the lowered module's alias
    table.

    `fn` may be a plain callable (donate_argnums tells the pass what the
    caller INTENDS to donate; the pass jits with keep_unused=True so flat
    argument indices map 1:1 onto the lowered signature) or an
    already-jitted function (its own donate_argnums apply).

    Findings:
      donated_unaliased (warn)  — a donated buffer XLA did not alias: the
                                  donation silently degrades to a copy
                                  every call (shape/dtype matches no
                                  output, or the output went elsewhere).
      donatable (info)          — a large non-donated input whose exact
                                  shape+dtype appears among the outputs:
                                  if the caller never reads it after the
                                  call, donating it lets XLA reuse the
                                  buffer in place (the KV-pool pattern).
    """
    kwargs = kwargs or {}
    jitted = hasattr(fn, "lower") and hasattr(fn, "__wrapped__")
    if jitted:
        jfn = fn
    else:
        jfn = jax.jit(fn, donate_argnums=tuple(donate_argnums),
                      keep_unused=True)

    with warnings.catch_warnings(record=True) as wlog:
        warnings.simplefilter("always")
        lowered = jfn.lower(*args, **kwargs)
    text = lowered.as_text()
    n_args, aliases = parse_io_aliases(text)

    # flat leaves in call order, tagged with which top-level arg they
    # belong to and whether that arg was donated
    flat_leaves, _ = jax.tree.flatten((args, kwargs))
    donated_set = set()
    if jitted:
        # read intent from the pjit params (donated_invars is flat) —
        # reuse the caller's already-traced jaxpr when its top eqn is the
        # pjit of this function; re-trace only as a fallback
        try:
            cj = closed_jaxpr
            if cj is None or not cj.eqns \
                    or "donated_invars" not in cj.eqns[0].params:
                cj = jax.make_jaxpr(jfn)(*args, **kwargs)
            din = cj.eqns[0].params.get("donated_invars", ())
            donated_set = {i for i, d in enumerate(din) if d}
        except Exception:
            donated_set = set()
        flat_donated = [i in donated_set for i in range(len(flat_leaves))]
    else:
        flat_donated = []
        for ai, a in enumerate(args):
            leaves = jax.tree.flatten(a)[0]
            flat_donated += [ai in set(donate_argnums)] * len(leaves)
        flat_donated += [False] * (len(flat_leaves) - len(flat_donated))

    if not aliases and any(flat_donated):
        # No aliases in the StableHLO but donation was intended: under
        # SPMD partitioning the alias table is only established at
        # compile time (see parse_compiled_aliases) — compile before
        # claiming the donation degraded to a copy. Failure-path only:
        # executables whose donation lowered normally never pay this.
        try:
            aliases = parse_compiled_aliases(lowered.compile().as_text())
        except Exception:
            pass

    out: List[Finding] = []
    mapped = n_args == len(flat_leaves)
    if not mapped:
        # pruned/transformed signature: fall back to counting — every
        # donated invar should have produced one alias attr
        n_donated = sum(flat_donated)
        if n_donated and len(aliases) < n_donated:
            out.append(Finding(
                "donation", "donated_unaliased", "warn",
                f"{n_donated - len(aliases)} of {n_donated} donated "
                f"buffers have no input_output_alias in the lowered "
                f"module (silent copy per call)",
                executable=executable,
                data={"donated": n_donated, "aliased": len(aliases)}))
        return out

    out_avals = []
    if closed_jaxpr is None:
        try:
            closed_jaxpr = jax.make_jaxpr(jfn if jitted else fn)(
                *args, **kwargs)
        except Exception:
            closed_jaxpr = None
    if closed_jaxpr is not None:
        out_avals = [(tuple(v.aval.shape), str(v.aval.dtype))
                     for v in closed_jaxpr.jaxpr.outvars]

    for i, leaf in enumerate(flat_leaves):
        aval = jax.api_util.shaped_abstractify(leaf) \
            if not hasattr(leaf, "shape") else leaf
        nb = _nbytes(aval)
        key = (tuple(aval.shape), str(aval.dtype))
        if flat_donated[i]:
            if i not in aliases:
                out.append(Finding(
                    "donation", "donated_unaliased", "warn",
                    f"donated arg {i} ({aval.dtype}{list(aval.shape)}, "
                    f"{nb / 1e6:.2f} MB) has no input_output_alias — "
                    f"XLA copies it every call instead of updating in "
                    f"place",
                    where=f"arg[{i}]", executable=executable,
                    data={"arg": i, "shape": list(aval.shape),
                          "dtype": str(aval.dtype), "bytes": nb}))
        elif nb >= min_bytes and key in out_avals:
            out.append(Finding(
                "donation", "donatable", "info",
                f"arg {i} ({aval.dtype}{list(aval.shape)}, "
                f"{nb / 1e6:.2f} MB) is not donated but an output has "
                f"its exact shape+dtype — donate it if it is never read "
                f"after the call",
                where=f"arg[{i}]", executable=executable,
                data={"arg": i, "shape": list(aval.shape),
                      "dtype": str(aval.dtype), "bytes": nb}))
    # surface jax's own "donated buffers not usable" warning as data
    for w in wlog:
        if "donated" in str(w.message).lower():
            if not any(f.code == "donated_unaliased" for f in out):
                out.append(Finding(
                    "donation", "donated_unaliased", "warn",
                    str(w.message).split("\n")[0],
                    executable=executable))
    return out
