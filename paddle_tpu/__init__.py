"""paddle_tpu — a TPU-native deep learning framework.

Capability class of PaddlePaddle (reference snapshot surveyed in SURVEY.md),
re-designed for TPU: jax.Array storage, XLA compilation, pjit/shard_map
distribution over device meshes, and Pallas kernels for fused ops. The public
API mirrors `paddle.*` (reference: python/paddle/__init__.py) so reference
users can migrate; the implementation shares nothing with the reference's
CUDA/C++ architecture.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .core.dtype import (  # noqa: F401
    float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, uint16, uint32, uint64, bool_, complex64, complex128,
    float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, convert_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .core.random import seed  # noqa: F401
from .core import ops as _ops
from .core.ops import linalg, fft  # noqa: F401

# Re-export the whole op surface at top level, paddle-style.
_OP_EXPORTS = [
    n for n in dir(_ops)
    if not n.startswith("_") and callable(getattr(_ops, n))
    and n not in ("Tensor", "apply_op", "to_tensor", "partial", "lax", "convert_dtype",
                  "get_default_dtype", "linalg", "fft")
]
for _n in _OP_EXPORTS:
    globals()[_n] = getattr(_ops, _n)
del _n

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from .hapi import Model, summary  # noqa: F401,E402
from .framework.io import save, load  # noqa: F401,E402
from .tensor import tensor as _tensor_ns  # noqa: F401,E402
from . import static  # noqa: F401,E402
from .static.program import enable_static, disable_static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from .core.flags import set_flags, get_flags  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import fluid  # noqa: F401,E402
version = type("version", (), {"full_version": __version__,
                               "commit": "unknown",
                               "show": staticmethod(lambda: print(__version__))})


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    import jax
    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def in_dynamic_mode() -> bool:
    from .jit.api import _in_jit_trace
    from .static.program import in_static_mode
    return not _in_jit_trace() and not in_static_mode()


def set_device(device: str):
    from .device import set_device as _sd
    return _sd(device)


def get_device() -> str:
    from .device import get_device as _gd
    return _gd()
