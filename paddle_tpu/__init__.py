"""paddle_tpu — a TPU-native deep learning framework.

Capability class of PaddlePaddle (reference snapshot surveyed in SURVEY.md),
re-designed for TPU: jax.Array storage, XLA compilation, pjit/shard_map
distribution over device meshes, and Pallas kernels for fused ops. The public
API mirrors `paddle.*` (reference: python/paddle/__init__.py) so reference
users can migrate; the implementation shares nothing with the reference's
CUDA/C++ architecture.
"""
from __future__ import annotations

__version__ = "0.1.0"

# Integer-dtype contract: paddle's default integer dtype is int64
# (reference: python/paddle/tensor/creation.py to_tensor — int lists become
# int64). jax disables 64-bit types by default and silently truncates, which
# would give users silent 32-bit wraparound. We enable x64 so int64 is real;
# float defaults remain float32 because every creation op passes an explicit
# dtype (get_default_dtype()). See MIGRATION.md "Integer dtypes".
import jax as _jax  # noqa: E402

_jax.config.update("jax_enable_x64", True)

# jax version compat: `jax.shard_map` became a top-level export after 0.4.x
# (with `axis_names=` selecting the manually-mapped mesh axes and
# `check_vma=` replacing `check_rep=`); older runtimes ship the previous
# signature under jax.experimental. Install a translating alias before any
# submodule does `from jax import shard_map`.
if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _esm

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, axis_names=None,
                          check_vma=None, check_rep=None, auto=None):
        if auto is None and axis_names is not None and mesh is not None:
            # new API names the MAPPED axes; old API names the AUTO rest
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        cr = (check_rep if check_rep is not None
              else check_vma if check_vma is not None else True)
        kw = {"check_rep": cr}
        if auto:
            kw["auto"] = frozenset(auto)
        return _esm(f, mesh, in_specs, out_specs, **kw)

    _jax.shard_map = _shard_map_compat

from .core.dtype import (  # noqa: F401
    float16, bfloat16, float32, float64, int8, int16, int32, int64,
    uint8, uint16, uint32, uint64, bool_, complex64, complex128,
    float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, convert_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.autograd import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from .core.random import seed  # noqa: F401
from .core import ops as _ops
from . import linalg, fft, signal  # noqa: F401

# Re-export the whole op surface at top level, paddle-style.
_OP_EXPORTS = [
    n for n in dir(_ops)
    if not n.startswith("_") and callable(getattr(_ops, n))
    and n not in ("Tensor", "apply_op", "to_tensor", "partial", "lax", "convert_dtype",
                  "get_default_dtype", "linalg", "fft")
]
for _n in _OP_EXPORTS:
    globals()[_n] = getattr(_ops, _n)
del _n

from . import nn  # noqa: F401,E402
from . import optimizer  # noqa: F401,E402
from . import io  # noqa: F401,E402
from . import amp  # noqa: F401,E402
from . import jit  # noqa: F401,E402
from . import metric  # noqa: F401,E402
from . import distributed  # noqa: F401,E402
from . import autograd  # noqa: F401,E402
from . import framework  # noqa: F401,E402
from . import device  # noqa: F401,E402
from . import vision  # noqa: F401,E402
from . import distribution  # noqa: F401,E402
from . import hapi  # noqa: F401,E402
from . import incubate  # noqa: F401,E402
from .hapi import Model, summary  # noqa: F401,E402
from .framework.io import save, load  # noqa: F401,E402
from .tensor import tensor as _tensor_ns  # noqa: F401,E402
from . import static  # noqa: F401,E402
from .static.program import enable_static, disable_static  # noqa: F401,E402
from . import inference  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import obs  # noqa: F401,E402
from . import debugging  # noqa: F401,E402
from . import analysis  # noqa: F401,E402
from . import resilience  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from .core.flags import set_flags, get_flags  # noqa: F401,E402
from . import onnx  # noqa: F401,E402
from . import utils  # noqa: F401,E402
from . import fluid  # noqa: F401,E402
version = type("version", (), {"full_version": __version__,
                               "commit": "unknown",
                               "show": staticmethod(lambda: print(__version__))})


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    import jax
    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def in_dynamic_mode() -> bool:
    from .jit.api import _in_jit_trace
    from .static.program import in_static_mode
    return not _in_jit_trace() and not in_static_mode()


def set_device(device: str):
    from .device import set_device as _sd
    return _sd(device)


def get_device() -> str:
    from .device import get_device as _gd
    return _gd()


# ---------------------------------------------------------------------------
# Top-level surface completion (reference python/paddle/__init__.py __all__):
# places, attrs, RNG state, and small framework utilities.

from .fluid import (  # noqa: E402,F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, NPUPlace, XPUPlace, ParamAttr)
from .distributed import DataParallel  # noqa: E402,F401

bool = bool_  # noqa: A001  — paddle.bool dtype alias
dtype = __import__("numpy").dtype  # paddle.dtype(x) — dtype constructor


def iinfo(dtype):  # noqa: A002
    import numpy as _np
    from .core.dtype import convert_dtype as _cd
    return _np.iinfo(_cd(dtype))


def finfo(dtype):  # noqa: A002
    import numpy as _np
    from .core.dtype import convert_dtype as _cd
    return _np.finfo(_cd(dtype))


def get_rng_state():
    """reference: paddle.get_rng_state — opaque generator state blob."""
    from .core import random as _r
    return _r.get_state()


def set_rng_state(state):
    from .core import random as _r
    return _r.set_state(state)


# single-accelerator runtime: the device RNG *is* the host-threaded threefry
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: paddle.set_printoptions — Tensor repr goes through numpy."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """reference: paddle.create_parameter (fluid/layers/tensor.py)."""
    from .nn import initializer as I
    init = default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    data = init(list(shape), dtype)
    p = Parameter(data._data if isinstance(data, Tensor) else data)
    if name:
        p.name = name
    return p


class LazyGuard:
    """reference: paddle.LazyGuard — defers parameter materialization.
    Here parameters are host numpy/jax arrays materialized on first device
    use by XLA anyway, so the guard only needs to be a scope marker."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def disable_signal_handler():
    """reference: paddle.disable_signal_handler — no native signal handlers
    are installed in this runtime; compat no-op."""


def check_shape(shape):
    """reference: input-shape validator used by creation APIs."""
    for s in (shape.tolist() if isinstance(shape, Tensor) else list(shape)):
        if int(s) < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")


def batch(reader, batch_size, drop_last=False):
    """reference: paddle.batch (legacy reader decorator)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def flops(net, input_size, custom_ops=None, print_detail=False):
    """reference: paddle.flops (hapi/dynamic_flops.py) — per-layer FLOPs
    estimate via a forward pass with hooks."""
    import numpy as _np
    from .nn.layer import Layer
    from .nn.layers.common import Linear
    from .nn.layers.conv import Conv2D

    total = [0]

    def count(layer, x, y):
        if isinstance(layer, Linear):
            rows = x[0].size // x[0].shape[-1]
            total[0] += 2 * rows * layer.weight.shape[0] * layer.weight.shape[1]
        elif isinstance(layer, Conv2D):
            # 2 * (Cin/groups * kh * kw) MACs per output element
            k = int(_np.prod(layer.weight.shape[1:]))
            total[0] += 2 * k * int(_np.prod(y.shape))
        return None

    hooks = []
    for sub in net.sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(count))
    x = randn(list(input_size))
    was_training = net.training
    net.eval()
    net(x)
    if was_training:
        net.train()
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]
