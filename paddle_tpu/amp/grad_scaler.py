"""GradScaler (reference: python/paddle/amp/grad_scaler.py).

Dynamic loss scaling for fp16; with bf16 (TPU default) scaling is disabled by
default since bf16 shares fp32's exponent range — the API still works so
reference training scripts run unchanged.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.ops import multiply, isfinite, all as _all


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio, self._decr_ratio = incr_ratio, decr_ratio
        self._incr_every, self._decr_every = incr_every_n_steps, decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled_opts = set()

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        return multiply(loss, Tensor(jnp.asarray(self._scale, loss._data.dtype)))

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled_opts:
            return  # already unscaled this step (e.g. user clipped grads first)
        self._unscaled_opts.add(id(optimizer))
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._param_list:
            if p.grad is None:
                continue
            g = p.grad._data * inv
            if not bool(jnp.all(jnp.isfinite(g))):
                found = True
            p.grad._data = g
        self._found_inf = found

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled_opts.clear()

    def get_loss_scaling(self):
        return self._scale

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def set_state_dict(self, state):
        self._scale = state.get("scale", self._scale)
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
