"""GradScaler (reference: python/paddle/amp/grad_scaler.py).

Dynamic loss scaling for fp16; with bf16 (TPU default) scaling is disabled by
default since bf16 shares fp32's exponent range — the API still works so
reference training scripts run unchanged.

Numerics-observability rewrite (r8): the found-inf decision is the IN-GRAPH
sentinel ``debugging.found_inf`` — one fused reduction over the whole grad
pytree instead of the old per-parameter ``bool(jnp.all(...))`` scan that
paid a device->host sync per parameter. The scale/good/bad bookkeeping is a
pure ``jnp.where`` rule (``_update_rule``) shared verbatim by the eager
``update()`` path and by ``jit.TrainStep(scaler=...)``, which threads
(scale, good_steps, bad_steps) through the compiled step as carry — so the
loss-scale trajectory is identical eager vs jit (tested), and under jit the
whole decision stays on device: the update is select-skipped on overflow
with zero host round trips.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor


def _f(x) -> float:
    """Host float of a maybe-device scalar (the only sync points are the
    explicit user reads that call this)."""
    return float(np.asarray(x))


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio, self._decr_ratio = incr_ratio, decr_ratio
        self._incr_every, self._decr_every = incr_every_n_steps, decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf_arr = None   # device bool scalar from the sentinel
        self._unscaled_opts = set()

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        from ..core.ops import multiply
        return multiply(loss, Tensor(jnp.asarray(self._scale, loss._data.dtype)))

    # ------------------------------------------------------------------
    # found-inf: ONE in-graph reduction, read lazily
    @property
    def _found_inf(self):
        """Host view of the sentinel (one sync, memoized per step)."""
        if self._found_inf_arr is None:
            return False
        if not isinstance(self._found_inf_arr, bool):
            self._found_inf_arr = bool(np.asarray(self._found_inf_arr))
        return self._found_inf_arr

    @_found_inf.setter
    def _found_inf(self, v):
        self._found_inf_arr = v

    def unscale_(self, optimizer):
        if not self._enable:
            return
        if id(optimizer) in self._unscaled_opts:
            return  # already unscaled this step (e.g. user clipped grads first)
        self._unscaled_opts.add(id(optimizer))
        inv = jnp.float32(1.0) / jnp.asarray(self._scale, jnp.float32)
        grads = []
        for p in optimizer._param_list:
            if p.grad is None:
                continue
            p.grad._data = p.grad._data * inv.astype(p.grad._data.dtype)
            grads.append(p.grad._data)
        from ..debugging import found_inf
        # device scalar; NOT synced here — step() reads it once
        self._found_inf_arr = found_inf(grads)

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()
        optimizer.clear_grad()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    # ------------------------------------------------------------------
    # the pure scale-update rule, shared by eager update() and TrainStep's
    # in-graph path (reference semantics: update_loss_scaling_op)
    @staticmethod
    def _update_rule(scale, good, bad, found, *, incr_ratio, decr_ratio,
                     incr_every, decr_every):
        """(scale, good, bad, found) -> (scale', good', bad'); all jnp
        scalars, trace-safe (pure jnp.where)."""
        found = jnp.asarray(found)
        bad2 = jnp.where(found, bad + 1, 0)
        good2 = jnp.where(found, 0, good + 1)
        dec = bad2 >= decr_every
        inc = jnp.logical_and(jnp.logical_not(found), good2 >= incr_every)
        scale2 = jnp.where(
            dec, jnp.maximum(scale * decr_ratio, 1.0),
            jnp.where(inc, scale * incr_ratio, scale))
        return (scale2.astype(jnp.float32),
                jnp.where(inc, 0, good2).astype(jnp.int32),
                jnp.where(dec, 0, bad2).astype(jnp.int32))

    def _hyper(self) -> dict:
        return dict(incr_ratio=self._incr_ratio, decr_ratio=self._decr_ratio,
                    incr_every=self._incr_every, decr_every=self._decr_every)

    # state threading for jit.TrainStep(scaler=...)
    def state_arrays(self):
        """(scale f32, good i32, bad i32) jnp scalars for the compiled step."""
        return (jnp.asarray(self._scale, jnp.float32),
                jnp.asarray(self._good_steps, jnp.int32),
                jnp.asarray(self._bad_steps, jnp.int32))

    def set_state_arrays(self, state, found_inf=None):
        """Adopt the step's output state WITHOUT a host sync (device scalars
        are kept; user reads like get_loss_scaling() sync lazily)."""
        self._scale, self._good_steps, self._bad_steps = state
        if found_inf is not None:
            self._found_inf_arr = found_inf

    def update(self):
        if not (self._enable and self._dynamic):
            return
        found = self._found_inf_arr if self._found_inf_arr is not None else False
        self._scale, self._good_steps, self._bad_steps = self._update_rule(
            jnp.asarray(self._scale, jnp.float32),
            jnp.asarray(self._good_steps, jnp.int32),
            jnp.asarray(self._bad_steps, jnp.int32),
            found, **self._hyper())
        self._found_inf_arr = None
        self._unscaled_opts.clear()

    def get_loss_scaling(self):
        return _f(self._scale)

    def state_dict(self):
        return {"scale": _f(self._scale), "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "good_steps": int(_f(self._good_steps)),
                "bad_steps": int(_f(self._bad_steps))}

    def set_state_dict(self, state):
        self._scale = state.get("scale", _f(self._scale))
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


AmpScaler = GradScaler
