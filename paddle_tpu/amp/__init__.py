"""Automatic mixed precision (reference: python/paddle/amp/ — auto_cast at
auto_cast.py:296, GradScaler at grad_scaler.py; C++ hooks in
eager_amp_auto_cast.h).

TPU-native stance: bf16 is the native matmul dtype, so AMP here is a dtype
*policy* rather than a per-op rewrite pass. `auto_cast` installs a policy the
eager op layer consults for MXU-bound ops (matmul/conv); O2 additionally casts
parameters. GradScaler keeps the reference API; on bf16 loss scaling is
mathematically unnecessary (8-bit exponent), so with bf16 it is a transparent
pass-through unless the user forces fp16 semantics.
"""
from .auto_cast import auto_cast, amp_guard, get_amp_state, white_list  # noqa: F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

from ..core.tensor import _install_amp_hook
_install_amp_hook()

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate"]


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """Reference: paddle.amp.decorate — O2 casts model params to the low
    dtype (master weights kept fp32 inside optimizer states, which our
    optimizers already do by keeping fp32 moments and computing in fp32)."""
    from ..nn.layer import Layer
    if level == "O2":
        single = isinstance(models, Layer)
        mlist = [models] if single else list(models)
        for m in mlist:
            m.to(dtype=dtype)
        models = mlist[0] if single else mlist
    if optimizers is None:
        return models
    return models, optimizers

from . import debugging  # noqa: E402,F401
