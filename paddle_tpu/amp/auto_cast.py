"""auto_cast context (reference: python/paddle/amp/auto_cast.py:296 amp_guard,
fp16_lists.py white/black lists)."""
from __future__ import annotations

import contextlib
import threading

from ..core.dtype import convert_dtype

# Ops that should run in low precision (MXU-bound) — analog of the reference
# white list (amp/fp16_lists.py): matmul/conv/attention.
white_list = {"matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
              "conv2d_transpose", "einsum", "sdpa", "flash_attention", "addmm"}
# Ops that must stay fp32 (reductions / losses / norms / exp-like).
black_list = {"softmax", "log_softmax", "cross_entropy", "layer_norm", "batch_norm",
              "group_norm", "instance_norm", "rms_norm", "sum", "mean", "logsumexp",
              "exp", "log", "pow", "norm", "mse_loss", "bce", "bce_with_logits",
              "nll_loss", "kl_div", "cosine_similarity"}

_state = threading.local()


class _AmpState:
    __slots__ = ("enabled", "dtype", "level", "custom_white", "custom_black")

    def __init__(self, enabled=False, dtype=None, level="O1",
                 custom_white=(), custom_black=()):
        self.enabled = enabled
        self.dtype = dtype
        self.level = level
        self.custom_white = set(custom_white or ())
        self.custom_black = set(custom_black or ())


def get_amp_state() -> _AmpState:
    st = getattr(_state, "amp", None)
    return st if st is not None else _AmpState()


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = getattr(_state, "amp", None)
    _state.amp = _AmpState(enable, convert_dtype(dtype), level,
                           custom_white_list, custom_black_list)
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast


def amp_dest_dtype(op_name, st=None):
    """The policy decision alone: target dtype for op inputs, or None.
    Shared by the eager cast and the static-mode record/replay cast."""
    import jax.numpy as jnp
    st = st or get_amp_state()
    if not st.enabled:
        return None
    wl = (white_list | st.custom_white) - st.custom_black
    bl = (black_list | st.custom_black) - st.custom_white
    if op_name in wl or (st.level == "O2" and op_name not in bl):
        return st.dtype
    if op_name in bl:
        return jnp.float32
    return None


def _should_cast(dtype, dest):
    import jax.numpy as jnp
    if dest is None or not jnp.issubdtype(dtype, jnp.floating):
        return False
    if dest == jnp.float32:
        return dtype in (jnp.bfloat16, jnp.float16)
    return dtype != jnp.float64


def amp_cast_inputs(op_name, arrays, st=None):
    """Called from the eager op path: cast inputs per active policy."""
    dest = amp_dest_dtype(op_name, st)
    if dest is None:
        return arrays
    return [a.astype(dest) if hasattr(a, "dtype") and _should_cast(a.dtype, dest)
            else a for a in arrays]
