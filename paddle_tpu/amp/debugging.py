"""paddle.amp.debugging — numeric debugging helpers.

Reference: python/paddle/amp/debugging.py (check_numerics,
enable_operator_stats_collection, TensorCheckerConfig) over the C++
check_numerics kernels. Here check_numerics is an eager scan (the
FLAGS_check_nan_inf machinery, SURVEY §5.2) and the collection toggles
flip the same flag.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core import flags as _flags


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode=None):
    """Raise on NaN/Inf in `tensor` (reference: amp/debugging.py
    check_numerics)."""
    arr = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    if np.issubdtype(arr.dtype, np.floating) and not np.isfinite(arr).all():
        n_nan = int(np.isnan(arr).sum())
        n_inf = int(np.isinf(arr).sum())
        raise FloatingPointError(
            f"check_numerics: {op_type or 'tensor'} {var_name} contains "
            f"{n_nan} NaN and {n_inf} Inf values")
    return tensor


def enable_tensor_checker(config=None):
    _flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    _flags.set_flags({"FLAGS_check_nan_inf": False})


def enable_operator_stats_collection():
    _flags.set_flags({"FLAGS_benchmark": True})


def disable_operator_stats_collection():
    _flags.set_flags({"FLAGS_benchmark": False})


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=None, checked_op_list=None,
                 skipped_op_list=None, **kw):
        self.enable = enable
