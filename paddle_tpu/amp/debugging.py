"""paddle.amp.debugging — numeric debugging helpers (compatibility facade).

Reference: python/paddle/amp/debugging.py (check_numerics,
check_layer_numerics, enable_operator_stats_collection, TensorCheckerConfig)
over the C++ check_numerics kernels / FLAGS_check_nan_inf machinery
(SURVEY §5.2).

As of r8 this module is a FACADE over paddle_tpu.debugging — the in-graph
numerics-observability subsystem. The reference semantics are kept
(check_numerics raises FloatingPointError with NaN/Inf counts; the
enable/disable toggles flip FLAGS_check_nan_inf), but the counting is one
on-device reduction (debugging.sentinel.array_stats) instead of a host
numpy scan, check_layer_numerics exists and instruments real per-layer
sentinels, and TensorCheckerConfig translates into a
debugging.NumericsConfig usable with jit.TrainStep(numerics=...) — the
path that works INSIDE a compiled step, where the eager scan never could.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..core.tensor import Tensor
from ..core import flags as _flags
from .. import debugging as _dbg


class DebugMode:
    """reference: paddle.amp.debugging.DebugMode values."""
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 4


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode=None):
    """Raise on NaN/Inf in `tensor` (reference: amp/debugging.py
    check_numerics). One device reduction + one host read — not an
    elementwise numpy scan. Inside a jit trace this cannot branch on data;
    use TrainStep(numerics=...) / check_layer_numerics there instead."""
    import jax
    import jax.numpy as jnp
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    arr = np.asarray(arr) if not hasattr(arr, "dtype") else arr
    # jnp.floating (not np.) so bfloat16 tensors are checked too
    if not jnp.issubdtype(arr.dtype, jnp.floating):
        return tensor
    if isinstance(arr, jax.core.Tracer):
        return tensor   # trace-time: covered by the in-graph sentinels
    row = np.asarray(_dbg.array_stats(arr))
    n_nan, n_inf = int(row[1]), int(row[2])
    if n_nan or n_inf:
        raise FloatingPointError(
            f"check_numerics: {op_type or 'tensor'} {var_name} contains "
            f"{n_nan} NaN and {n_inf} Inf values")
    return tensor


def check_layer_numerics(model, root: Optional[str] = None):
    """Instrument `model`'s sublayers with the in-graph numerics sentinels
    (reference: paddle.amp.debugging.check_layer_numerics decorator). Works
    eagerly (wrap forwards in debugging.collect_stats()) AND under jit
    (TrainStep's numerics mode reads the same hooks). Returns the handle
    (`.paths`, `.remove()`)."""
    return _dbg.check_layer_numerics(model, root=root)


class TensorCheckerConfig:
    """reference: paddle.amp.debugging.TensorCheckerConfig — kept as the
    legacy configuration bag; `to_numerics_config()` maps it onto the new
    subsystem for use with jit.TrainStep(numerics=...)."""

    def __init__(self, enable=True, debug_mode=None, output_dir=None,
                 checked_op_list=None, skipped_op_list=None, **kw):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list

    def to_numerics_config(self) -> Optional[_dbg.NumericsConfig]:
        if not self.enable:
            return None
        abort = self.debug_mode in (None, DebugMode.CHECK_NAN_INF_AND_ABORT)
        return _dbg.NumericsConfig(
            every_n_steps=1, dump_dir=self.output_dir,
            raise_on_nonfinite=abort)


_checker_config: Optional[TensorCheckerConfig] = None


def enable_tensor_checker(config: Optional[TensorCheckerConfig] = None):
    """reference semantics: turn the per-op NaN/Inf scan on. Also stashes
    `config` so TrainStep(numerics=True) picks up its abort/dump policy via
    get_tensor_checker_config()."""
    global _checker_config
    _checker_config = config
    _flags.set_flags({"FLAGS_check_nan_inf": True})


def disable_tensor_checker():
    global _checker_config
    _checker_config = None
    _flags.set_flags({"FLAGS_check_nan_inf": False})


def get_tensor_checker_config() -> Optional[TensorCheckerConfig]:
    return _checker_config


def enable_operator_stats_collection():
    _flags.set_flags({"FLAGS_benchmark": True})


def disable_operator_stats_collection():
    _flags.set_flags({"FLAGS_benchmark": False})
