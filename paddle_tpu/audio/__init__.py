"""paddle.audio analog — audio features and functional DSP.

Reference (SURVEY §2.3): python/paddle/audio/ — features (Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC layers) and functional
(get_window, compute_fbank_matrix, hz↔mel, power_to_db, create_dct).
TPU-native: STFT as frame+window+rfft in pure jnp — framing lowers to one
gather and the FFT batch runs on-device; no torchaudio-style C++ kernels.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..nn.layer import Layer


# ---------------------------------------------------------------- functional
def hz_to_mel(freq, htk=False):
    """reference: audio/functional/functional.py hz_to_mel."""
    if htk:
        return 2595.0 * np.log10(1.0 + np.asarray(freq) / 700.0)
    freq = np.asarray(freq, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (freq - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(freq >= min_log_hz,
                    min_log_mel + np.log(np.maximum(freq, 1e-10) / min_log_hz) / logstep,
                    mels)


def mel_to_hz(mel, htk=False):
    if htk:
        return 700.0 * (10.0 ** (np.asarray(mel) / 2595.0) - 1.0)
    mel = np.asarray(mel, dtype=np.float64)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return np.where(mel >= min_log_mel,
                    min_log_hz * np.exp(logstep * (mel - min_log_mel)), freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk), n_mels)
    return mel_to_hz(mels, htk)


def fft_frequencies(sr, n_fft):
    return np.linspace(0, sr / 2, 1 + n_fft // 2)


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney"):
    """Mel filterbank [n_mels, 1+n_fft//2] (reference:
    audio/functional/functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = fft_frequencies(sr, n_fft)
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return weights.astype(np.float32)


def create_dct(n_mfcc, n_mels, norm="ortho"):
    """DCT-II matrix [n_mels, n_mfcc] (reference: functional.py create_dct)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[:, None]
    dct = np.cos(math.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return dct.T.astype(np.float32)


def get_window(window: str, win_length: int, fftbins=True):
    """hann/hamming/blackman/ones (reference: functional/window.py)."""
    N = win_length + (0 if fftbins else -1)
    n = np.arange(win_length, dtype=np.float64)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * math.pi * n / max(N, 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * math.pi * n / max(N, 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * math.pi * n / max(N, 1))
             + 0.08 * np.cos(4 * math.pi * n / max(N, 1)))
    elif window in ("ones", "rectangular", "boxcar"):
        w = np.ones(win_length)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return w.astype(np.float32)


def power_to_db(magnitude, ref_value=1.0, amin=1e-10, top_db=80.0):
    """reference: functional.py power_to_db."""
    def fn(x):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, x))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    if isinstance(magnitude, Tensor):
        return apply_op("power_to_db", fn, [magnitude])
    return np.asarray(fn(jnp.asarray(magnitude)))


def _stft(x, n_fft, hop_length, win, center=True, power=2.0):
    """[B, T] → [B, 1+n_fft//2, frames] magnitude^power."""
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)], mode="reflect")
    T = x.shape[-1]
    n_frames = 1 + (T - n_fft) // hop_length
    idx = (jnp.arange(n_frames)[:, None] * hop_length +
           jnp.arange(n_fft)[None, :])
    frames = x[..., idx] * win  # [B, frames, n_fft]
    spec = jnp.fft.rfft(frames, axis=-1)
    mag = jnp.abs(spec) ** power
    return jnp.swapaxes(mag, -1, -2)


# ---------------------------------------------------------------- features
class Spectrogram(Layer):
    """reference: audio/features/layers.py Spectrogram."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        w = get_window(window, self.win_length)
        if self.win_length < n_fft:  # center-pad window to n_fft
            lpad = (n_fft - self.win_length) // 2
            w = np.pad(w, (lpad, n_fft - self.win_length - lpad))
        self._win = jnp.asarray(w)
        self.power = power
        self.center = center

    def forward(self, x):
        n_fft, hop, win, center, power = (self.n_fft, self.hop_length,
                                          self._win, self.center, self.power)

        def fn(a):
            return _stft(a, n_fft, hop, win, center, power)
        return apply_op("spectrogram", fn, [x])


class MelSpectrogram(Layer):
    """reference: features/layers.py MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spec = Spectrogram(n_fft, hop_length, win_length, window,
                                 power, center)
        self._fbank = jnp.asarray(compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self._spec(x)
        fbank = self._fbank

        def fn(s):
            return jnp.einsum("mf,...ft->...mt", fbank, s)
        return apply_op("mel_spectrogram", fn, [spec])


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._mel = MelSpectrogram(sr, n_fft, hop_length, win_length, window,
                                   power, center, n_mels, f_min, f_max, htk, norm)
        self._ref, self._amin, self._top_db = ref_value, amin, top_db

    def forward(self, x):
        return power_to_db(self._mel(x), self._ref, self._amin, self._top_db)


class MFCC(Layer):
    """reference: features/layers.py MFCC."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 top_db=None, dtype="float32"):
        super().__init__()
        self._logmel = LogMelSpectrogram(sr, n_fft, hop_length, win_length,
                                         window, power, center, n_mels, f_min,
                                         f_max, htk, norm, top_db=top_db)
        self._dct = jnp.asarray(create_dct(n_mfcc, n_mels))

    def forward(self, x):
        lm = self._logmel(x)
        dct = self._dct

        def fn(s):
            return jnp.einsum("mk,...mt->...kt", dct, s)
        return apply_op("mfcc", fn, [lm])


functional = type("functional", (), {
    "hz_to_mel": staticmethod(hz_to_mel), "mel_to_hz": staticmethod(mel_to_hz),
    "mel_frequencies": staticmethod(mel_frequencies),
    "fft_frequencies": staticmethod(fft_frequencies),
    "compute_fbank_matrix": staticmethod(compute_fbank_matrix),
    "create_dct": staticmethod(create_dct),
    "get_window": staticmethod(get_window),
    "power_to_db": staticmethod(power_to_db),
})
features = type("features", (), {
    "Spectrogram": Spectrogram, "MelSpectrogram": MelSpectrogram,
    "LogMelSpectrogram": LogMelSpectrogram, "MFCC": MFCC,
})

from . import backends  # noqa: E402,F401
from . import datasets  # noqa: E402,F401
from .backends import load, save, info  # noqa: E402,F401
