"""Audio IO backend (reference: python/paddle/audio/backends — wave_backend
load/save/info built on the stdlib wave module; soundfile is optional there
and absent here).

Integer PCM WAV only (8/16/32-bit) — stdlib wave cannot read IEEE-float
WAVs; that matches the reference's default wave_backend without soundfile.
"""
from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np

__all__ = ["AudioInfo", "info", "load", "save", "list_available_backends",
           "get_current_backend", "set_backend"]


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str


_PCM = {1: np.uint8, 2: np.int16, 4: np.int32}


def info(filepath: str) -> AudioInfo:
    """reference: wave_backend.info."""
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8,
                         encoding=f"PCM_{f.getsampwidth() * 8}")


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """reference: wave_backend.load → (waveform, sample_rate). With
    `normalize` the result is float32 in [-1, 1]."""
    with wave.open(filepath, "rb") as f:
        sr, nch, width = f.getframerate(), f.getnchannels(), f.getsampwidth()
        f.setpos(min(frame_offset, f.getnframes()))
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(max(0, n))
    dt = _PCM.get(width)
    if dt is None:
        raise ValueError(f"unsupported PCM width {width}")
    data = np.frombuffer(raw, dtype=dt).reshape(-1, nch)
    if width == 1:  # unsigned 8-bit: center
        data = data.astype(np.float32) - 128.0
        scale = 128.0
    else:
        scale = float(2 ** (width * 8 - 1))
        data = data.astype(np.float32)
    if normalize:
        data = data / scale
    out = data.T if channels_first else data
    return out, sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         bits_per_sample: int = 16):
    """reference: wave_backend.save — float input in [-1, 1] → PCM."""
    data = np.asarray(src, np.float32)
    if data.ndim == 1:
        data = data[None, :] if channels_first else data[:, None]
    if channels_first:
        data = data.T                                  # [n, ch]
    if bits_per_sample != 16:
        raise ValueError("wave backend writes PCM_16 only (like the "
                         "reference without soundfile)")
    pcm = np.clip(data, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(data.shape[1])
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(pcm.tobytes())


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            "only wave_backend is available (no soundfile in this "
            "environment); reference parity: audio/backends/init_backend.py")
