"""Audio datasets (reference: python/paddle/audio/datasets — TESS, ESC50
over AudioClassificationDataset).

Zero-egress environment: datasets read a LOCAL directory laid out like the
published archives (pass `data_dir=`); there is no downloader. Feature modes
mirror the reference: 'raw' waveforms or on-the-fly mel features via
paddle_tpu.audio feature layers.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..io.dataset import Dataset
from . import backends

__all__ = ["AudioClassificationDataset", "TESS", "ESC50"]


class AudioClassificationDataset(Dataset):
    """reference: audio/datasets/dataset.py — files + labels, optional
    feature extraction per __getitem__."""

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: str = "raw", sample_rate: Optional[int] = None,
                 **feat_kwargs):
        self.files = list(files)
        self.labels = list(labels)
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self._feat_kwargs = feat_kwargs
        self._feat_layers = {}  # sr -> constructed feature layer
        if feat_type not in ("raw", "melspectrogram", "mfcc"):
            raise ValueError(f"unknown feat_type {feat_type!r}")

    def _features(self, wav: np.ndarray, sr: int) -> np.ndarray:
        if self.feat_type == "raw":
            return wav
        import paddle_tpu as paddle
        from . import MelSpectrogram, MFCC
        layer = self._feat_layers.get(sr)
        if layer is None:  # fbank/DCT matrices are per-sr; build once
            layer = (MelSpectrogram if self.feat_type == "melspectrogram"
                     else MFCC)(sr=sr, **self._feat_kwargs)
            self._feat_layers[sr] = layer
        x = paddle.to_tensor(wav[None, :].astype("float32"))
        return np.asarray(layer(x)._data)[0]

    def __getitem__(self, idx) -> Tuple[np.ndarray, int]:
        wav, sr = backends.load(self.files[idx], channels_first=True)
        if self.sample_rate is not None and sr != self.sample_rate:
            raise ValueError(
                f"{self.files[idx]}: sample rate {sr} != expected "
                f"{self.sample_rate} (no resampler in wave backend)")
        return self._features(wav[0], sr), self.labels[idx]

    def __len__(self):
        return len(self.files)


def _scan_wavs(data_dir: str, what: str) -> List[str]:
    if not data_dir or not os.path.isdir(data_dir):
        raise RuntimeError(
            f"{what} needs a local archive: pass data_dir= pointing at the "
            "extracted dataset (this environment has no network downloader; "
            "reference downloads via paddle.dataset.common)")
    out = []
    for root, _, names in os.walk(data_dir):
        out.extend(os.path.join(root, n) for n in names
                   if n.lower().endswith(".wav"))
    if not out:
        raise RuntimeError(f"no .wav files under {data_dir}")
    # full-path sort: os.walk directory order is filesystem-dependent, and
    # fold assignment must be reproducible across machines
    return sorted(out)


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set (reference: audio/datasets/tess.py).
    Label = emotion, parsed from `..._<emotion>.wav` filenames."""

    EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw", data_dir: str = None,
                 **kwargs):
        if not 1 <= split <= n_folds:
            raise ValueError("split must be in [1, n_folds]")
        files = _scan_wavs(data_dir, "TESS")
        labels = []
        for f in files:
            emo = os.path.basename(f).rsplit("_", 1)[-1][:-4].lower()
            labels.append(self.EMOTIONS.index(emo)
                          if emo in self.EMOTIONS else 0)
        fold = np.arange(len(files)) % n_folds + 1
        keep = (fold != split) if mode == "train" else (fold == split)
        super().__init__([f for f, k in zip(files, keep) if k],
                         [l for l, k in zip(labels, keep) if k],
                         feat_type=feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference: audio/datasets/esc50.py).
    Label + fold parsed from `<fold>-<src>-<take>-<target>.wav` names."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", data_dir: str = None, **kwargs):
        files = _scan_wavs(data_dir, "ESC50")
        keep_files, labels = [], []
        for f in files:
            parts = os.path.basename(f)[:-4].split("-")
            try:
                fold, target = int(parts[0]), int(parts[-1])
            except (ValueError, IndexError):
                continue
            is_train = fold != split
            if (mode == "train") == is_train:
                keep_files.append(f)
                labels.append(target)
        super().__init__(keep_files, labels, feat_type=feat_type, **kwargs)
