"""High-level training API (reference: python/paddle/hapi/)."""
from .model import Model
from .summary import summary
from . import callbacks

__all__ = ["Model", "summary", "callbacks"]
