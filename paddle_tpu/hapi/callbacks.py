"""Training callbacks (reference: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "EarlyStopping", "LRScheduler", "ReduceLROnPlateau", "VisualDL",
           "ProfilerCallback", "NumericsCallback", "PreemptionCallback",
           "config_callbacks"]


class Callback:
    """reference callbacks.py:71."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cbk):
        self.callbacks.append(cbk)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def on_begin(self, mode, logs=None):
        self._call(f"on_{mode}_begin", logs)

    def on_end(self, mode, logs=None):
        self._call(f"on_{mode}_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_begin", step, logs)

    def on_batch_end(self, mode, step, logs=None):
        self._call(f"on_{mode}_batch_end", step, logs)


class ProgBarLogger(Callback):
    """stdout progress logging (reference callbacks.py:278)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose
        self.epoch = 0
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if k == "step":
                continue
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, np.ndarray)):
                parts.append(f"{k}: {np.asarray(v).round(4).tolist()}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose >= 2 and step % self.log_freq == 0:
            print(f"epoch {self.epoch} step {step}: {self._fmt(logs)}", flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.time() - self._t0
            print(f"epoch {epoch} done in {dt:.1f}s: {self._fmt(logs)}", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose >= 1:
            print(f"eval: {self._fmt(logs)}", flush=True)


class ModelCheckpoint(Callback):
    """periodic save (reference callbacks.py:531)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """reference callbacks.py:625."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True,
                 save_dir=None):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.save_dir = save_dir
        self.stopped_epoch = 0
        if mode == "max" or (mode == "auto" and ("acc" in monitor
                                                 or monitor.endswith("score"))):
            self.monitor_op = np.greater
        else:
            self.monitor_op = np.less
        self.best = None
        self.wait = 0

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_epoch_end(self, epoch, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            current = (logs or {}).get(f"eval_{self.monitor}")
        if current is None:
            return
        current = float(np.asarray(current).reshape(-1)[0])
        delta = self.min_delta if self.monitor_op == np.greater else -self.min_delta
        if self.best is None or self.monitor_op(current - delta, self.best):
            self.best = current
            self.wait = 0
            save_dir = self.save_dir or self.params.get("save_dir")
            if self.save_best_model and save_dir:
                self.model.save(os.path.join(save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True
                if self.verbose:
                    print(f"early stopping at epoch {epoch}", flush=True)


class LRScheduler(Callback):
    """steps an optimizer.lr.LRScheduler (reference callbacks.py:445)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step and self._sched() is not None:
            self._sched().step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch and self._sched() is not None:
            self._sched().step()


class ReduceLROnPlateau(Callback):
    """reference callbacks.py:727."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.monitor_op = np.greater if mode == "max" or \
            (mode == "auto" and "acc" in monitor) else np.less
        self.best = None
        self.wait = 0
        self.cooldown_counter = 0

    def on_epoch_end(self, epoch, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        current = float(np.asarray(current).reshape(-1)[0])
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        delta = self.min_delta if self.monitor_op == np.greater \
            else -self.min_delta
        if self.best is None or self.monitor_op(current - delta, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                opt = self.model._optimizer
                old = opt.get_lr()
                new = max(old * self.factor, self.min_lr)
                if old - new > 1e-12:
                    opt.set_lr(new)
                    if self.verbose:
                        print(f"reduce lr to {new:.2e}", flush=True)
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """scalar logging to a directory of .jsonl files (the reference logs to
    VisualDL; that dependency isn't in this image, so logs stay greppable)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        import json
        os.makedirs(self.log_dir, exist_ok=True)
        rec = {k: float(np.asarray(v).reshape(-1)[0]) for k, v in (logs or {}).items()
               if isinstance(v, numbers.Number)}
        rec["step"] = self._step
        self._step += 1
        with open(os.path.join(self.log_dir, "train.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")


class ProfilerCallback(Callback):
    """Drives the observability layer through Model.fit (reference analog:
    paddle.profiler used as a fit callback).

    `profiler`: a paddle_tpu.profiler.Profiler — started at train begin,
    stepped per batch (its scheduler decides when the device trace
    records), stopped at train end.
    `monitor`: a profiler.StepMonitor — brackets every train batch, so fit
    runs get step-time/MFU/HBM/recompile telemetry (and its JSONL export /
    on_report hook) with zero changes to the training loop. The monitor's
    report() is printed at train end when `summary=True`; when a device
    trace was captured, its compute/comm overlap ratio is fed into the
    monitor (`overlap_ratio` gauge) so the number is tracked, not
    table-only.
    `timeline`: a profiler.timeline.SpanRecorder — installed process-wide
    for the duration of fit, so the goodput seams (TrainStep compile/step
    spans, DataLoader input stalls, CheckpointManager blocking/drain)
    attribute the run's wall clock; eval passes are recorded per eval
    batch as `eval` badput.
    `telemetry`: an obs.TelemetryServer (ISSUE 12) — for the duration of
    fit the callback registers this run's exposition producers into the
    server's collision-checked registry, so a TRAINING job is scrapeable
    over the wire exactly like a serving replica: the StepMonitor gauges,
    and (when a timeline is attached) LIVE goodput gauges stitched from
    the in-memory recorder on every scrape — no waiting for the segment
    files. Producers unregister at train end; the server's lifecycle
    (start/close) stays with the caller.
    `flightrec`: an obs.FlightRecorder (ISSUE 17) — attached to the
    monitor for the duration of fit (anomaly rows — recompiles,
    stragglers, numerics events — pin profiler captures of the next
    steps) and, when `telemetry` is given, mounted as its /profilez
    route; detached and unmounted at train end."""

    def __init__(self, profiler=None, monitor=None, summary=True,
                 timeline=None, telemetry=None, flightrec=None):
        super().__init__()
        self.profiler = profiler
        self.monitor = monitor
        self.summary = summary
        self.timeline = timeline
        self.telemetry = telemetry
        self.flightrec = flightrec
        if flightrec is not None and monitor is None:
            raise ValueError("flightrec needs a monitor: the recorder "
                             "advances at the monitor's step brackets")
        self._tl_prev = None
        self._eval_t0 = None
        self._tele_registered = []

    def _live_goodput_text(self):
        """One scrape = one stitch of the live recorder's ring. A young
        recorder (no spans yet) renders nothing rather than failing the
        whole /metrics page."""
        from ..profiler.goodput import GoodputReport
        if not self.timeline.spans():
            return ""
        return GoodputReport(self.timeline).metrics_text()

    def on_train_begin(self, logs=None):
        if self.telemetry is not None:
            reg = self.telemetry.registry
            for name, producer in (
                    ("train_monitor",
                     self.monitor.metrics_text if self.monitor is not None
                     else None),
                    ("train_goodput",
                     self._live_goodput_text if self.timeline is not None
                     else None)):
                if producer is None:
                    continue
                # a fit that died mid-epoch (Preempted, chaos) never ran
                # on_train_end: its stale producer may still be
                # registered — adopt the slot rather than erroring the
                # new cycle (same contract as the timeline restore below)
                reg.unregister(name)
                reg.register(name, producer)
                if name not in self._tele_registered:
                    self._tele_registered.append(name)
        if self.timeline is not None:
            from ..profiler import timeline as _tlmod
            prev = _tlmod.install(self.timeline)
            # a fit that died mid-epoch (Preempted, chaos) never runs
            # on_train_end, so this callback's own recorder can still be
            # installed from the previous cycle — restoring "prev" would
            # then self-reference. Treat that as nothing-to-restore.
            self._tl_prev = None if prev is self.timeline else prev
        if self.flightrec is not None:
            if getattr(self.monitor, "flightrec", None) is not \
                    self.flightrec:     # died-mid-fit idempotence, as
                self.flightrec.attach(monitor=self.monitor)  # above
            if self.telemetry is not None:
                self.telemetry.add_route("/profilez",
                                         self.flightrec.profilez)
        if self.profiler is not None:
            self.profiler.start()

    def on_train_batch_begin(self, step, logs=None):
        if self.monitor is not None:
            self.monitor.begin_step()

    def on_train_batch_end(self, step, logs=None):
        if self.monitor is not None:
            self.monitor.end_step()
        if self.profiler is not None:
            self.profiler.step()

    def on_eval_batch_begin(self, step, logs=None):
        # per-BATCH spans (not one per eval pass): the loader fetch runs
        # between batches, so its input_wait spans never nest inside
        # eval spans — conservation needs the seams non-overlapping
        tl = self.timeline
        if tl is None:
            from ..profiler.timeline import current as _tl_current
            tl = _tl_current()
        self._eval_t0 = (tl, tl.now()) if tl is not None else None

    def on_eval_batch_end(self, step, logs=None):
        if self._eval_t0 is not None:
            tl, t0 = self._eval_t0
            tl.record("eval", t0, tl.now())
            self._eval_t0 = None

    def on_train_end(self, logs=None):
        # drop the telemetry producers FIRST (the monitor/timeline they
        # read outlive fit, but a dead run must not keep advertising)
        if self.telemetry is not None:
            for name in self._tele_registered:
                self.telemetry.registry.unregister(name)
            self._tele_registered = []
        if self.flightrec is not None:
            if self.telemetry is not None:
                self.telemetry.remove_route("/profilez")
            self.flightrec.detach()
        # restore the timeline FIRST: a profiler.stop() failure must not
        # leak this fit's recorder into the process-wide slot
        if self.timeline is not None:
            from ..profiler import timeline as _tlmod
            _tlmod.install(self._tl_prev)
            self._tl_prev = None
        if self.profiler is not None:
            self.profiler.stop()
            if self.monitor is not None and not self.profiler.timer_only:
                # surface the captured trace's compute/comm overlap as
                # the tracked `overlap_ratio` gauge, and its per-
                # collective ledger rows (ISSUE 13) as the labeled
                # collective_* gauges — the decomposition dashboards
                # track per op (best effort: CPU fit runs may capture no
                # device lanes)
                try:
                    from ..profiler.trace_analysis import analyze
                    an = analyze(self.profiler._trace_dir)
                    ov = an.overlap()
                    if ov.get("ratio") is not None:
                        self.monitor.record_overlap(ov)
                    rows = an.collective_rows()
                    if rows:
                        self.monitor.record_collectives(rows)
                except Exception:
                    pass
        if self.monitor is not None and self.summary:
            import json
            print("StepMonitor: " + json.dumps(self.monitor.report()),
                  flush=True)


class NumericsCallback(Callback):
    """Training-health sibling of ProfilerCallback: drives the
    paddle_tpu.debugging numerics layer through Model.fit.

    Two regimes, picked automatically per batch:

      - fused (Model's TrainStep path): the compiled step already carries
        the in-graph stats tree; this callback just attaches the
        NumericsConfig (detector/dump/monitor cadence) to that TrainStep.
      - eager tape loop: every `every_n_steps` batches the callback reduces
        the model's parameter grads to a stats tree on device (one fetch)
        and feeds the same detector.

    In both regimes the per-batch loss feeds the loss-spike detector and
    events land in `detector.events` (+ the StepMonitor JSONL stream when a
    monitor is attached). `raise_on_event=True` aborts training on any
    event — the FLAGS_check_nan_inf abort policy."""

    def __init__(self, numerics=None, every_n_steps=1, dump_dir=None,
                 monitor=None, raise_on_event=False):
        super().__init__()
        from ..debugging import NumericsConfig
        if numerics is None:
            numerics = NumericsConfig(every_n_steps=every_n_steps,
                                      dump_dir=dump_dir, monitor=monitor)
        self.numerics = NumericsConfig.coerce(numerics)
        self.raise_on_event = raise_on_event
        self._step = 0
        self._attached = None

    @property
    def detector(self):
        return self.numerics.detector

    @property
    def events(self):
        return self.numerics.detector.events

    def _train_step(self):
        return getattr(self.model, "_fused_step", None)

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        ts = self._train_step()
        if ts is not None and self._attached is not ts:
            # adopt the fused step: its compiled executables are rebuilt
            # with the stats tree as outputs on the next batch
            ts.set_numerics(self.numerics)
            self._attached = ts
            return
        if ts is not None:
            if self.raise_on_event and self.numerics.detector.events:
                raise FloatingPointError(
                    f"numerics anomaly: {self.numerics.detector.events[-1]!r}")
            return
        # eager tape regime
        n = max(1, self.numerics.every_n_steps or 1)
        if self._step % n:
            return
        from ..debugging import model_param_stats
        net = getattr(self.model, "network", self.model)
        # grads if the loop kept them; else the params themselves (the
        # eager fit clears grads before callbacks run — a poisoned update
        # still shows as non-finite PARAMS on the next batch)
        tree = model_param_stats(net, grads=True)
        gn = None
        if len(tree):
            gn = float(np.sqrt(sum(r["l2"] ** 2 for _, r in tree.rows())))
        else:
            tree = model_param_stats(net, grads=False)
        loss = (logs or {}).get("loss")
        loss = float(np.asarray(loss).reshape(-1)[0]) if loss is not None \
            else None
        events = self.numerics.detector.observe(
            self._step, tree=tree if len(tree) else None,
            loss=loss, grad_norm=gn)
        mon = self.numerics.monitor
        if mon is not None and hasattr(mon, "record_numerics"):
            mon.record_numerics(step=self._step, loss=loss, grad_norm=gn,
                                events=events)
        for e in events:
            if self.numerics.on_event is not None:
                self.numerics.on_event(e)
        if events and self.raise_on_event:
            raise FloatingPointError(f"numerics anomaly: {events[0]!r}")


class _EagerFitState:
    """Emergency-checkpoint adapter for the eager (non-fused) fit path:
    host snapshot of the network's parameters, the optimizer's
    array/scalar state and the global RNG key. Without it a preemption on
    the eager path would exit with the resume-me code having checkpointed
    NOTHING — the supervisor would free-restart a job that loses all work
    every cycle. Resume is Model.load-style: restore the dict and
    set_state_dict the pieces."""

    def __init__(self, model, step):
        self._model = model
        self._step = int(step or 0)

    def state_dict(self):
        from ..core.tensor import Tensor
        from ..resilience.state import rng_state_dict
        out = {"step": self._step,
               "model": dict(self._model.network.state_dict()),
               "rng": rng_state_dict()}
        opt = getattr(self._model, "_optimizer", None)
        if opt is not None and hasattr(opt, "state_dict"):
            out["optimizer"] = {
                k: v for k, v in opt.state_dict().items()
                if isinstance(v, (Tensor, int, float, dict))}
        return out


class PreemptionCallback(Callback):
    """Preemption handling for Model.fit (resilience layer, ISSUE 7):
    polls a resilience.PreemptionHandler at every train-batch end, so a
    SIGTERM delivered mid-fit finishes the in-flight batch, takes one
    emergency checkpoint and exits with the resume-me code
    (Preempted/SystemExit — fleet.elastic.run_with_restarts restarts and
    the next fit resumes from the checkpoint).

        handler = resilience.PreemptionHandler(manager=mgr, state=ts)
        with handler:
            model.fit(..., callbacks=[PreemptionCallback(handler)])

    Without an explicit `state` on the handler, the emergency checkpoint
    snapshots the Model's fused TrainStep when fit runs the fused path
    (params/opt/step); on the eager tape path it snapshots the network's
    parameters + optimizer state + RNG host-side — either way a
    preempted fit makes durable progress before asking to be restarted
    (the resume-me exit code is a promise to the restart supervisor that
    restarting is not a lost cause)."""

    def __init__(self, handler, install=True):
        super().__init__()
        self.handler = handler
        self._install = install
        self._gstep = 0

    def on_train_begin(self, logs=None):
        if self._install:
            self.handler.install()
        # eager-path step numbering must be MONOTONIC across epochs and
        # restarts: fit's batch index resets to 0 every epoch, so using
        # it raw lets an older epoch's step_00000009 shadow a newer
        # epoch's step_00000002 in restore_latest(). Count completed
        # batches, starting above whatever the manager already holds.
        base = None
        mgr = getattr(self.handler, "manager", None)
        if mgr is not None:
            try:
                base = mgr.latest_step()
            except Exception:
                base = None
        self._gstep = int(base or 0)

    def on_train_batch_end(self, step, logs=None):
        self._gstep += 1
        state = self.handler.state
        if state is None:
            state = getattr(self.model, "_fused_step", None)
        if state is None:
            state = _EagerFitState(self.model, self._gstep)
        self.handler.poll(state=state)

    def on_train_end(self, logs=None):
        if self._install:
            self.handler.uninstall()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    """reference callbacks.py:35."""
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    cbk_list.set_params({"batch_size": batch_size, "epochs": epochs,
                         "steps": steps, "verbose": verbose,
                         "save_dir": save_dir, "metrics": metrics or []})
    return cbk_list
