"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from ..nn.layer import Layer

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}.

    reference model_summary.py:26 — we run a real forward with hooks-free
    introspection (pre/post wrappers around each leaf layer's forward).
    """
    rows = []
    handles = []

    def wrap(layer, name):
        orig = layer.forward

        def wrapped(*a, **kw):
            out = orig(*a, **kw)
            n_params = sum(int(np.prod(p.shape)) for p in layer.parameters(
                include_sublayers=False))
            out_shape = list(out.shape) if hasattr(out, "shape") else "-"
            rows.append((name, type(layer).__name__, out_shape, n_params))
            return out

        layer.forward = wrapped
        handles.append((layer, orig))

    for name, sub in net.named_sublayers():
        if not list(sub.sublayers()):  # leaves only
            wrap(sub, name)

    try:
        if input is not None:
            x = input
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = input_size if isinstance(input_size, list) else [input_size]
            dts = dtypes if isinstance(dtypes, (list, tuple)) else \
                [dtypes] * len(sizes)
            xs = [paddle.zeros(list(s), dtype=d or "float32")
                  for s, d in zip(sizes, dts)]
            x = xs if len(xs) > 1 else xs[0]
        was_training = net.training
        net.eval()
        net(*x) if isinstance(x, list) else net(x)
        if was_training:
            net.train()
    finally:
        for layer, orig in handles:
            layer.forward = orig

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w_name = max([len(r[0]) for r in rows] + [10])
    w_type = max([len(r[1]) for r in rows] + [10])
    print(f"{'Layer':<{w_name}}  {'Type':<{w_type}}  {'Output Shape':<20}  Params")
    print("-" * (w_name + w_type + 36))
    for name, tname, shape, n in rows:
        print(f"{name:<{w_name}}  {tname:<{w_type}}  {str(shape):<20}  {n:,}")
    print("-" * (w_name + w_type + 36))
    print(f"Total params: {total:,}\nTrainable params: {trainable:,}")
    return {"total_params": total, "trainable_params": trainable}
