"""paddle.Model — fit/evaluate/predict facade (reference: hapi/model.py:1004
Model.fit, :255 DynamicGraphAdapter).

TPU-native single adapter: eager tape steps (the jit.TrainStep fusion path is
available separately); no static/dygraph duality is needed because everything
lowers through XLA anyway.
"""
from __future__ import annotations

import os

import numpy as np

from ..core.tensor import Tensor
from ..core import autograd
from .. import framework
from ..io import DataLoader, Dataset
from . import callbacks as cbks


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _item(x):
    return float(x) if np.ndim(x) == 0 else np.asarray(x)


class Model:
    """Wraps a Layer with train/eval/predict loops, checkpointing, callbacks.

    Mirrors the reference surface: prepare(), fit(), evaluate(), predict(),
    train_batch(), eval_batch(), predict_batch(), save(), load(), parameters(),
    summary().
    """

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._use_fused = None
        self._fused_step = None
        self.stop_training = False

    # -- configuration ---------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None,
                use_fused_step=None):
        """reference: hapi/model.py Model.prepare. `use_fused_step`: True
        compiles fwd+bwd+update into one XLA program per step
        (jit.TrainStep); None (default) enables it automatically when no
        per-batch metrics need the network outputs; False keeps the eager
        tape loop."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        self._use_fused = use_fused_step
        self._fused_step = None
        return self

    # -- per-batch steps -------------------------------------------------
    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = _to_list(labels)
        if callable(self._loss):
            loss = self._loss(*outs, *labs)
        else:
            raise ValueError("loss not set; call prepare(loss=...)")
        return loss

    def _fused_eligible(self, update):
        if not update or self._metrics:
            return False
        use = getattr(self, "_use_fused", None)
        return use is None or use

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins, labs = _to_list(inputs), _to_list(labels)
        if self._fused_eligible(update):
            if self._fused_step is None:
                from ..jit.train_step import TrainStep
                n_in = len(ins)
                net, loss_fn = self.network, self._loss

                def fused_loss(*batch):
                    outs = net(*batch[:n_in])
                    return loss_fn(*_to_list(outs), *batch[n_in:])

                self._fused_step = TrainStep(net, self._optimizer, fused_loss)
            loss = self._fused_step(*ins, *labs)
            return [_item(np.asarray(loss._data))]
        outputs = self.network(*ins)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([_item(np.asarray(loss._data))], metrics) if metrics else \
            [_item(np.asarray(loss._data))]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        with autograd.no_grad():
            outputs = self.network(*_to_list(inputs))
            loss = self._compute_loss(outputs, labels) if self._loss else None
        metrics = self._update_metrics(outputs, labels)
        out = [_item(np.asarray(loss._data))] if loss is not None else []
        return (out, metrics) if metrics else out

    def predict_batch(self, inputs):
        self.network.eval()
        with autograd.no_grad():
            outputs = self.network(*_to_list(inputs))
        return [np.asarray(o._data) for o in _to_list(outputs)]

    def _update_metrics(self, outputs, labels):
        outs = _to_list(outputs)
        labs = _to_list(labels)
        results = []
        for metric in self._metrics:
            state = metric.compute(*outs, *labs)
            results.append(metric.update(*_to_list(state)))
        return results

    # -- loops -----------------------------------------------------------
    def _make_loader(self, data, batch_size, shuffle, num_workers):
        if isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        raise TypeError(f"expected Dataset or DataLoader, got {type(data)}")

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None):
        """reference hapi/model.py:1004."""
        train_loader = self._make_loader(train_data, batch_size, shuffle,
                                         num_workers)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers) if eval_data is not None \
            else None
        cbk_list = cbks.config_callbacks(
            callbacks, model=self, epochs=epochs, verbose=verbose,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            metrics=["loss"] + [m.name() for m in self._metrics])
        cbk_list.on_begin("train")
        self.stop_training = False
        for epoch in range(epochs):
            if self.stop_training:
                break
            cbk_list.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbk_list, "train")
            if eval_loader is not None and epoch % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          callbacks=callbacks)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            # epoch_end sees eval_* metrics so EarlyStopping/ReduceLROnPlateau
            # can monitor validation
            cbk_list.on_epoch_end(epoch, logs)
            if save_dir and epoch % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
        if save_dir:
            self.save(os.path.join(save_dir, "final"))
        cbk_list.on_end("train")
        return self

    def _run_one_epoch(self, loader, cbk_list, mode):
        for metric in self._metrics:
            metric.reset()
        logs = {}
        for step, batch in enumerate(loader):
            batch = _to_list(batch)
            # convention: last element is the label(s)
            ins, labs = (batch[:-1], batch[-1]) if len(batch) > 1 else (batch, None)
            cbk_list.on_batch_begin(mode, step, logs)
            if mode == "train":
                result = self.train_batch(ins, labs)
            else:
                result = self.eval_batch(ins, labs)
            if isinstance(result, tuple):
                losses, _ = result
            else:
                losses = result
            if losses:
                logs["loss"] = losses[0]
            for metric in self._metrics:
                logs[metric.name()] = metric.accumulate()
            logs["step"] = step
            cbk_list.on_batch_end(mode, step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None):
        loader = self._make_loader(eval_data, batch_size, False, num_workers)
        cbk_list = cbks.config_callbacks(
            callbacks, model=self, verbose=verbose, log_freq=log_freq,
            metrics=["loss"] + [m.name() for m in self._metrics])
        cbk_list.on_begin("eval")
        logs = self._run_one_epoch(loader, cbk_list, "eval")
        cbk_list.on_end("eval", logs)
        return {k: v for k, v in logs.items() if k != "step"}

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            batch = _to_list(batch)
            # like the reference, an (input, label) dataset is allowed for
            # predict: keep the declared inputs, else drop a trailing label
            if self._inputs is not None:
                batch = batch[:len(_to_list(self._inputs))]
            elif len(batch) > 1 and self._loss is not None:
                batch = batch[:-1]
            outputs.append(self.predict_batch(batch))
        # transpose list-of-batches to per-output lists
        outs = list(zip(*outputs)) if outputs else []
        if stack_outputs:
            outs = [np.concatenate(o) for o in outs]
        else:
            outs = [list(o) for o in outs]
        return outs

    # -- persistence -----------------------------------------------------
    def save(self, path, training=True):
        """reference hapi/model.py:1660 — `path + .pdparams/.pdopt`."""
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        framework.io.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            framework.io.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = framework.io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None \
                and os.path.exists(opt_path):
            self._optimizer.set_state_dict(framework.io.load(opt_path))
        return self

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary
        return summary(self.network, input_size, dtypes=dtype)
