"""Collective ledger + shard-wall stitching — per-collective and per-shard
attribution for multi-chip runs (ISSUE 13).

The r13 `overlap_ratio` gauge answers "is communication hidden under
compute, in aggregate?" — one scalar. The two consumers the distributed
scale-out work needs answer finer questions:

  CollectiveLedger   WHICH collective pays the exposed time. Wraps
                     `profiler.trace_analysis.collective_rows()` (name,
                     calls, bytes, bus bandwidth, overlapped-vs-EXPOSED
                     time per op) with the reporting surface every other
                     telemetry block has: `table()` for humans,
                     `metrics_text()` for the registry/scrape path, and
                     `summary()` for JSON. The T3 result (PAPERS.md arxiv
                     2401.16677) is that comm/compute scheduling wins live
                     at individual-collective granularity — this ledger is
                     the budget that work is judged against.

  shard walls        WHICH shard pays the step time. In single-controller
                     SPMD every host runs the same program and the
                     collective-synchronized step ends when the SLOWEST
                     shard does; each shard's own StepMonitor already
                     writes per-step JSONL rows, so `load_shard_walls`
                     stitches N shard files into per-step wall maps and
                     `feed_shard_walls` replays them through
                     `StepMonitor.record_shard_steps` — skew gauges plus
                     the transition-based structured straggler event.

Both are pure host-side accounting: build them from a captured trace or
from JSONL files after (or during) the run; nothing here touches device
state.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from ..profiler._metrics import gauge_lines

__all__ = ["CollectiveLedger", "load_shard_walls", "feed_shard_walls"]


class CollectiveLedger:
    """Per-collective attribution rows from one captured device trace.

        ledger = CollectiveLedger.from_trace(trace_dir, steps=N)
        print(ledger.table())
        registry.register("collectives", ledger.metrics_text)

    `rows` is `trace_analysis.collective_rows()` output: one dict per
    collective op with dur_us/busy_us/overlapped_us/exposed_us,
    exposed_frac, bytes and bus_gbps (None when the capture carries no
    byte stats). `steps` divides the rendered table into per-step
    figures; the exposition always reports whole-capture seconds.
    """

    def __init__(self, rows: List[dict], *, steps: Optional[int] = None,
                 overlap: Optional[dict] = None):
        self.rows = [dict(r) for r in rows]
        self.steps = steps
        self.overlap = dict(overlap) if overlap else None

    # ------------------------------------------------------- construction
    @classmethod
    def from_analysis(cls, analysis, steps: Optional[int] = None
                      ) -> "CollectiveLedger":
        """From a trace_analysis.TraceAnalysis (steps defaults to its)."""
        return cls(analysis.collective_rows(),
                   steps=steps if steps is not None else analysis.steps,
                   overlap=analysis.overlap())

    @classmethod
    def from_trace(cls, path_or_events, steps: Optional[int] = None
                   ) -> "CollectiveLedger":
        """From a trace file / capture directory / traceEvents list."""
        from ..profiler.trace_analysis import analyze
        return cls.from_analysis(analyze(path_or_events, steps=steps))

    @classmethod
    def from_static(cls, rows: List[dict], steps: Optional[int] = None
                    ) -> "CollectiveLedger":
        """Wrap a STATIC collective inventory
        (analysis.sharding.collective_inventory / TrainStep.comm_audit
        rows) in the ledger's reporting surface: same table and gauges —
        including the wire-dtype column and the bytes-by-dtype split the
        int8 gradient sync is judged on — with the clock columns rendered
        as '-' (nothing ran)."""
        return cls(rows, steps=steps)

    # ---------------------------------------------------------- reporting
    def totals(self) -> dict:
        # static inventory rows carry no clock — their busy/exposed is
        # None, not 0 (nothing ran), so the sums skip them
        busy = sum(r["busy_us"] for r in self.rows
                   if r.get("busy_us") is not None)
        exposed = sum(r["exposed_us"] for r in self.rows
                      if r.get("exposed_us") is not None)
        nbytes = [r["bytes"] for r in self.rows if r["bytes"] is not None]
        return {"collectives": len(self.rows),
                "busy_us": busy,
                "exposed_us": exposed,
                "exposed_frac": exposed / busy if busy else 0.0,
                "bytes": sum(nbytes) if nbytes else None}

    def by_dtype(self) -> Dict[str, dict]:
        """{wire_dtype: {"calls", "bytes"}} over rows that carry a dtype
        (static inventory rows; runtime trace rows don't) — the
        int8-vs-f32 gradient-sync split as one aggregation."""
        out: Dict[str, dict] = {}
        for r in self.rows:
            dt = r.get("dtype")
            if not dt:
                continue
            g = out.setdefault(dt, {"calls": 0, "bytes": 0})
            g["calls"] += int(r.get("calls", 1))
            if r.get("bytes") is not None:
                g["bytes"] += int(r["bytes"])
        return out

    def summary(self) -> dict:
        return {"rows": [dict(r) for r in self.rows],
                "totals": self.totals(),
                "overlap": self.overlap,
                "steps": self.steps}

    def table(self, top: int = 20) -> str:
        from ..profiler.trace_analysis import format_collective_rows
        n = self.steps
        div = max(n or 1, 1)
        unit = "ms/step" if n else "ms"
        lines = ["---- Collective ledger ----"]
        if not self.rows:
            lines.append("no collective ops in capture "
                         "(single-chip step)")
            return "\n".join(lines)
        lines += format_collective_rows(self.rows, steps=n, top=top)
        t = self.totals()
        lines.append(f"exposed total {t['exposed_us'] / div / 1e3:.3f} "
                     f"{unit} ({t['exposed_frac'] * 100:.1f}% of "
                     f"collective busy time)")
        return "\n".join(lines)

    def check_static(self, static_rows: List[dict],
                     rtol: float = 0.01) -> List[dict]:
        """Cross-check this runtime ledger against a STATIC collective
        inventory (analysis.sharding.collective_inventory / a
        TrainStep.comm_audit's rows): per collective kind, the bytes the
        trace measured must match the bytes the HLO promised within
        `rtol`. Returns the analysis.sharding.diff_ledgers rows; kinds
        disagree when the runtime capture carries no byte stats, when a
        scan body multiplies trip counts the static side counts once, or
        when the deployed executable is NOT the one that was audited —
        all three are things a preflight gate wants to scream about.
        This ledger's `steps` normalizes the runtime side to per-step
        figures (static rows are per-step by construction)."""
        from ..analysis.sharding import diff_ledgers
        return diff_ledgers(static_rows, self.rows, steps=self.steps,
                            rtol=rtol)

    def metrics_text(self, prefix: str = "paddle_tpu_comm") -> str:
        """Registry-composable exposition: per-op labeled gauges + the
        exposed-time roll-up, rendered from the series table shared with
        StepMonitor (trace_analysis.collective_series_lines). The
        default prefix keeps these family names
        (`paddle_tpu_comm_collective_*`) disjoint from the monitor's
        adopted block (`paddle_tpu_collective_*`), so a process may
        register a standalone ledger AND a monitor that has
        record_collectives'd the same rows without a registry
        collision."""
        from ..profiler.trace_analysis import collective_series_lines
        lines = collective_series_lines(self.rows, prefix)
        t = self.totals()
        lines += gauge_lines(prefix, "collective_exposed_ratio",
                             t["exposed_frac"],
                             "exposed collective time / collective busy "
                             "time (0 = fully hidden)")
        if self.overlap and self.overlap.get("ratio") is not None:
            lines += gauge_lines(prefix, "collective_overlap_ratio",
                                 self.overlap["ratio"],
                                 "fraction of collective time hidden "
                                 "under device compute")
        return "\n".join(lines) + "\n" if lines else ""


# ------------------------------------------------------------ shard walls

def load_shard_walls(path_or_paths, *, pattern: str = ".jsonl"
                     ) -> Dict[int, Dict[str, float]]:
    """Stitch per-shard StepMonitor JSONL streams into per-step wall maps.

    `path_or_paths`: a directory (every ``*<pattern>`` file inside is one
    shard's stream, shard id = the file's stem) or an explicit
    ``{shard_id: path}`` mapping. Rows are StepMonitor step records —
    anything with both ``step`` and ``wall_s`` counts; overlap/numerics/
    straggler rows in the same stream are skipped. Returns
    ``{step: {shard_id: wall_s}}`` with steps ascending — feed each value
    to `StepMonitor.record_shard_steps` (or use `feed_shard_walls`).
    """
    if isinstance(path_or_paths, dict):
        files = {str(k): v for k, v in path_or_paths.items()}
    else:
        files = {}
        for fn in sorted(os.listdir(path_or_paths)):
            if fn.endswith(pattern):
                shard = fn[:-len(pattern)]
                for pre in ("shard_", "shard-"):
                    if shard.startswith(pre):
                        shard = shard[len(pre):]
                files[shard] = os.path.join(path_or_paths, fn)
    by_step: Dict[int, Dict[str, float]] = {}
    for shard, path in files.items():
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "step" not in row or "wall_s" not in row:
                    continue
                by_step.setdefault(int(row["step"]), {})[shard] = \
                    float(row["wall_s"])
    return dict(sorted(by_step.items()))


def feed_shard_walls(monitor, walls_by_step: Dict[int, Dict[str, float]],
                     *, complete_only: bool = True) -> List[dict]:
    """Replay stitched shard walls through a StepMonitor's skew state
    machine, in step order. `complete_only` skips steps where some shard
    has no record yet (a shard mid-step or a torn tail line would read as
    an infinite-skew ghost straggler). Returns the skew dicts recorded."""
    out = []
    world = max((len(w) for w in walls_by_step.values()), default=0)
    for step, walls in walls_by_step.items():
        if complete_only and len(walls) < world:
            continue
        out.append(monitor.record_shard_steps(walls, step=step))
    return out
