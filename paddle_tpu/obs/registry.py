"""MetricsRegistry — one collision-checked Prometheus page per process.

Every exposition producer in the package renders its own block through
the shared `profiler._metrics` formatter (`ServingMetrics.metrics_text`,
`StepMonitor.metrics_text`, `GoodputReport.metrics_text`, the obs SLO
monitor). Until now composing them was caller-side string concatenation
— which silently breaks the moment two blocks emit the same metric
family (Prometheus drops or double-counts, depending on the scraper).
The registry is the composition point the telemetry server scrapes:

    reg = MetricsRegistry()
    reg.register("serving", engine.metrics.metrics_text)
    reg.register("goodput", report.metrics_text)
    page = reg.render()        # collision-checked, lint-clean, or raises

`render()` parses every producer's block (`_metrics.parse_exposition`),
REJECTS any metric family emitted by two producers (naming both), and
lints the merged page with the promtool-style checks below — so a bad
producer fails the scrape loudly instead of poisoning dashboards.

`lint_exposition(text)` is the pure-python promtool stand-in the tests
and the CI smoke leg run over endpoint payloads: structural invariants
from the parser plus per-type rules (counters end in `_total`, histogram
buckets cumulative with ascending `le` and `+Inf == _count`).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..profiler._metrics import ExpositionError, parse_exposition

__all__ = ["ExpositionError", "MetricsCollisionError", "MetricsRegistry",
           "lint_exposition"]


class MetricsCollisionError(ExpositionError):
    """Two registered producers emit the same metric family."""


def lint_exposition(text: str) -> dict:
    """Validate one exposition page; returns the parsed family dict.

    Checks (on top of `parse_exposition`'s structural grammar):
      - counter family names end in ``_total`` (the package convention —
        a counter that does not say so gets graphed as a gauge),
      - histogram families carry ``_sum`` and ``_count`` samples,
        bucket ``le`` bounds strictly ascend, bucket counts are
        cumulative (non-decreasing), the last bucket is ``+Inf`` and its
        count equals ``_count``.
    """
    families = parse_exposition(text)
    for name, fam in families.items():
        kind = fam["type"]
        if kind == "counter" and not name.endswith("_total"):
            raise ExpositionError(
                f"counter family {name} does not end in _total")
        if kind != "histogram":
            continue
        buckets: List[tuple] = []
        count = None
        has_sum = False
        for base, labels, value in fam["samples"]:
            if base == f"{name}_bucket":
                le = labels[1:-1].split("=", 1)[1].strip('"')
                buckets.append((le, float(value)))
            elif base == f"{name}_count":
                count = float(value)
            elif base == f"{name}_sum":
                has_sum = True
        if not buckets or count is None or not has_sum:
            raise ExpositionError(
                f"histogram {name} is missing bucket/_sum/_count samples")
        if buckets[-1][0] != "+Inf":
            raise ExpositionError(
                f"histogram {name}: last bucket must be le=\"+Inf\"")
        les = [float(le) for le, _ in buckets[:-1]]
        if any(b <= a for a, b in zip(les, les[1:])):
            raise ExpositionError(
                f"histogram {name}: le bounds must strictly ascend")
        counts = [c for _, c in buckets]
        if any(b < a for a, b in zip(counts, counts[1:])):
            raise ExpositionError(
                f"histogram {name}: bucket counts must be cumulative")
        if buckets[-1][1] != count:
            raise ExpositionError(
                f"histogram {name}: +Inf bucket ({buckets[-1][1]:.0f}) "
                f"!= _count ({count:.0f})")
    return families


class MetricsRegistry:
    """Named exposition producers -> one checked `/metrics` page.

    A producer is a zero-argument callable returning exposition text
    (typically a bound ``metrics_text``/``functools.partial`` carrying
    its prefix). Blocks render in registration order. The registry holds
    no metric state of its own — every ``render()`` re-invokes the
    producers, so the page is always live.

    Thread-safety: register/unregister and render take a snapshot of the
    producer dict under the GIL; producers themselves read host-side
    counters/gauges (plain dict reads), which is the same guarantee the
    JSONL/on_record paths already rely on.
    """

    def __init__(self):
        self._producers: Dict[str, Callable[[], str]] = {}

    def register(self, name: str, producer: Callable[[], str]):
        if not callable(producer):
            raise TypeError(f"producer for {name!r} must be a "
                            f"zero-argument callable returning exposition "
                            f"text; got {producer!r}")
        if name in self._producers:
            raise ValueError(f"producer {name!r} already registered "
                             f"(unregister it first)")
        self._producers[name] = producer
        return self

    def unregister(self, name: str) -> bool:
        return self._producers.pop(name, None) is not None

    @property
    def producers(self) -> List[str]:
        return list(self._producers)

    def render(self, *, validate: bool = True) -> str:
        """The merged page. Collision-checks family names across
        producers always; ``validate=True`` additionally lints every
        block (cheap: one regex pass per line at scrape rate)."""
        owners: Dict[str, str] = {}
        blocks: List[str] = []
        for name, producer in list(self._producers.items()):
            block = producer()
            if not block or not block.strip():
                continue
            fams = lint_exposition(block) if validate \
                else parse_exposition(block)
            for fam in fams:
                prev = owners.get(fam)
                if prev is not None:
                    raise MetricsCollisionError(
                        f"metric family {fam} emitted by both "
                        f"{prev!r} and {name!r} — give one producer a "
                        f"distinct prefix")
                owners[fam] = name
            blocks.append(block if block.endswith("\n") else block + "\n")
        return "".join(blocks)
