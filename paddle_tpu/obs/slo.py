"""Declarative SLOs over the serving histograms, evaluated as MULTI-WINDOW
BURN RATES — the alerting math of the SRE workbook, computed purely from
the log-bucket histograms the serving layer already keeps.

An SLO target like ``ttft_p99=0.5`` reads "99% of requests get their
first token within 0.5 s". Its error budget is 1%; the BURN RATE over a
window is (fraction of bad requests in the window) / budget — burn 1.0
spends the budget exactly at the objective's horizon, burn 14.4 spends a
30-day budget in 2 days. An alert fires only when BOTH the long and the
short window burn above the threshold: the long window proves the breach
is sustained (no paging on one slow request), the short window proves it
is STILL happening (no paging an hour after recovery).

Windowing over cumulative histograms: `SLOMonitor.poll()` snapshots each
target's (bad, total) counts; window deltas come from differencing the
newest snapshot against the one at/before the window's left edge. No
per-request retention — memory is O(snapshots within the long window).

Bad-count resolution is bucket-granular: a threshold inside a populated
bucket counts that bucket's observations as GOOD (the bucket's upper
bound is the effective threshold — relative slack bounded by the bucket
ratio, ~26% at the default 10/decade). Pin thresholds to bucket bounds
(or raise per_decade) where that slack matters.

Targets (`parse_slo` grammar, comma-separated ``key=value``):
  ``ttft_pNN`` / ``tpot_pNN`` / ``e2e_pNN`` / ``queue_pNN`` = latency
  bound in seconds (``500ms`` / ``2s`` suffixes accepted);
  ``goodput`` = completion-ratio floor in [0, 1): budget = 1 - floor,
  bad = terminal requests that did NOT complete (rejected / timeout /
  error) — the serving-side goodput; the training-side figure stays
  `tools/goodput_report.py --min-goodput`.

Alerts are STRUCTURED events through the metrics emission path (the
per-request JSONL stream / on_record hook): one ``{"slo_alert": ...}``
row on the transition into breach, one ``{"slo_clear": ...}`` row on
recovery — never a log-spam row per poll.
"""
from __future__ import annotations

import re
import threading
import time
from bisect import bisect_left
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

from ..profiler._metrics import (LogHistogram, counter_lines, format_value,
                                 gauge_lines)

__all__ = ["SLOTarget", "SLOMonitor", "parse_slo", "evaluate_slo",
           "format_slo_table"]

_HISTS = {"ttft": "ttft_seconds", "tpot": "tpot_seconds",
          "e2e": "e2e_seconds", "queue": "queue_seconds"}
_KEY_RE = re.compile(r"^(ttft|tpot|e2e|queue)_p(\d{1,2}(?:\.\d+)?)$")


@dataclass(frozen=True)
class SLOTarget:
    """One declarative objective. `hist` is None for the goodput floor."""
    name: str                   # "ttft_p99" | "goodput"
    objective: float            # fraction of requests that must be good
    hist: Optional[str] = None  # ServingMetrics histogram name
    threshold_s: Optional[float] = None   # latency bound (hist targets)

    @property
    def budget(self) -> float:
        return 1.0 - self.objective

    def describe(self) -> str:
        if self.hist is None:
            return f"goodput >= {self.objective:g}"
        return (f"{self.objective:.4g} of requests "
                f"{self.hist} <= {self.threshold_s:g}s")


def _parse_seconds(text: str) -> float:
    text = text.strip()
    if text.endswith("ms"):
        return float(text[:-2]) / 1e3
    if text.endswith("s"):
        return float(text[:-1])
    return float(text)


def parse_slo(spec: str) -> List[SLOTarget]:
    """``"ttft_p99=500ms,e2e_p99=2s,goodput=0.95"`` -> targets."""
    targets: List[SLOTarget] = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"SLO item {item!r} is not key=value")
        key, _, val = item.partition("=")
        key = key.strip()
        if key == "goodput":
            floor = float(val)
            if not (0.0 <= floor < 1.0):
                raise ValueError(f"goodput floor must be in [0, 1), "
                                 f"got {floor}")
            targets.append(SLOTarget("goodput", objective=floor))
            continue
        m = _KEY_RE.match(key)
        if not m:
            raise ValueError(
                f"unknown SLO key {key!r}: expected goodput or one of "
                f"{'/'.join(_HISTS)}_pNN")
        q = float(m.group(2)) / 100.0
        if not (0.0 < q < 1.0):
            raise ValueError(f"percentile out of range in {key!r}")
        targets.append(SLOTarget(key, objective=q,
                                 hist=_HISTS[m.group(1)],
                                 threshold_s=_parse_seconds(val)))
    if not targets:
        raise ValueError(f"no SLO targets in {spec!r}")
    return targets


def _hist_good_count(hist: LogHistogram, threshold: float) -> int:
    """Observations <= threshold, at bucket granularity: the bucket
    CONTAINING the threshold counts good — its upper bound is the
    effective threshold (module docstring). Anything less would flag
    requests BELOW the target as violations (100 requests at 450ms
    against a 500ms target must burn zero budget, whatever bucket
    boundary 500ms falls inside). The +Inf overflow bucket is the one
    exception: it has no upper bound to stand in for the threshold, so
    it always counts bad."""
    k = bisect_left(hist.bounds, threshold)
    return sum(hist.counts[:min(k + 1, len(hist.bounds))])


def _target_counts(target: SLOTarget, metrics) -> Tuple[int, int]:
    """(bad, total) for one target from a ServingMetrics instance."""
    if target.hist is None:
        total = metrics.counters["requests"]
        return total - metrics.counters["completed"], total
    h = metrics.hists[target.hist]
    return h.count - _hist_good_count(h, target.threshold_s), h.count


def evaluate_slo(targets: List[SLOTarget], metrics) -> List[dict]:
    """Whole-history evaluation (the serve_bench gate): burn over
    everything the metrics saw. `ok` iff burn <= 1.0 — i.e. the run as a
    whole met the objective."""
    rows = []
    for t in targets:
        bad, total = _target_counts(t, metrics)
        frac = bad / total if total else 0.0
        burn = frac / t.budget if t.budget > 0 else (
            0.0 if bad == 0 else float("inf"))
        rows.append({"target": t.name, "objective": t.describe(),
                     "total": total, "bad": bad,
                     "bad_fraction": round(frac, 6),
                     "attainment": round(1.0 - frac, 6),
                     "burn": round(burn, 4), "ok": burn <= 1.0})
    return rows


def format_slo_table(rows: List[dict], *, title: str = "SLO") -> str:
    lines = [f"---- {title} burn rates ----",
             f"  {'target':<12} {'total':>7} {'bad':>6} {'attain':>8} "
             f"{'burn':>8}  verdict"]
    for r in rows:
        lines.append(
            f"  {r['target']:<12} {r['total']:>7} {r['bad']:>6} "
            f"{r['attainment'] * 100:>7.2f}% {r['burn']:>8.2f}  "
            f"{'ok' if r['ok'] else 'BREACH'} ({r['objective']})")
    return "\n".join(lines)


class SLOMonitor:
    """Multi-window burn-rate evaluation over a live ServingMetrics.

    `poll()` at any cadence (the telemetry server's scrape, the engine
    loop, a timer thread): each call snapshots the targets' cumulative
    (bad, total) counts, evaluates both windows and manages the per-
    target breach state machine. `clock` is injectable — tests drive the
    windows deterministically.

    Defaults are the SRE-workbook page pair: long 1h / short 5m at burn
    14.4 (a 30-day budget gone in 2 days). For CI-scale runs pass small
    windows and burn_threshold ~1.
    """

    def __init__(self, targets, metrics, *,
                 long_s: float = 3600.0, short_s: float = 300.0,
                 burn_threshold: float = 14.4,
                 clock: Callable[[], float] = time.monotonic,
                 on_alert: Optional[Callable[[dict], None]] = None):
        if isinstance(targets, str):
            targets = parse_slo(targets)
        self.targets = list(targets)
        if not self.targets:
            raise ValueError("SLOMonitor needs at least one target")
        if not (0 < short_s <= long_s):
            raise ValueError(f"need 0 < short_s <= long_s, "
                             f"got {short_s}, {long_s}")
        self.metrics = metrics
        self.long_s = float(long_s)
        self.short_s = float(short_s)
        self.burn_threshold = float(burn_threshold)
        self.clock = clock
        self.on_alert = on_alert
        # snapshots: (t, {target_name: (bad, total)}) — pruned past the
        # long window (one extra kept as the left-edge anchor)
        self._snaps: List[Tuple[float, dict]] = []
        self._breaching = {t.name: False for t in self.targets}
        self.alerts: List[dict] = []            # alert AND clear events
        self.alerts_total = 0
        self._last_eval: List[dict] = []
        # the class docstring invites poll() from the telemetry server's
        # scrape path — a ThreadingHTTPServer runs handlers on multiple
        # threads, so the snapshot deque and the breach state machine
        # are serialized here (same contract as obs.TraceBuffer); alert
        # sinks fire OUTSIDE the lock so a slow JSONL write or hook
        # cannot stall a concurrent scrape
        self._lock = threading.Lock()

    # ------------------------------------------------------------ windows
    def _window_burn(self, name: str, budget: float, now: float,
                     window: float) -> Optional[float]:
        """Burn over [now - window, now] from snapshot deltas; None when
        the window saw no traffic."""
        newest = self._snaps[-1][1][name]
        edge = now - window
        anchor = None
        for t, counts in self._snaps:           # oldest -> newest
            if t <= edge:
                anchor = counts[name]
            else:
                break
        if anchor is None:
            # window predates history: burn over everything we have —
            # a monitor younger than its window alerts on its whole life
            anchor = self._snaps[0][1][name]
        dbad = newest[0] - anchor[0]
        dtotal = newest[1] - anchor[1]
        if dtotal <= 0:
            return None
        frac = dbad / dtotal
        if budget <= 0:
            return 0.0 if dbad == 0 else float("inf")
        return frac / budget

    def poll(self, now: Optional[float] = None) -> List[dict]:
        """Snapshot + evaluate; returns per-target window figures. Fires
        the structured alert/clear events on breach transitions."""
        now = self.clock() if now is None else float(now)
        counts = {t.name: _target_counts(t, self.metrics)
                  for t in self.targets}
        events: List[dict] = []
        with self._lock:
            if self._snaps and now < self._snaps[-1][0]:
                raise ValueError(f"poll time went backwards "
                                 f"({now} < {self._snaps[-1][0]})")
            self._snaps.append((now, counts))
            # prune: keep one snapshot at/before the long window's edge
            edge = now - self.long_s
            while len(self._snaps) >= 2 and self._snaps[1][0] <= edge:
                self._snaps.pop(0)
            out = []
            for t in self.targets:
                b_long = self._window_burn(t.name, t.budget, now,
                                           self.long_s)
                b_short = self._window_burn(t.name, t.budget, now,
                                            self.short_s)
                breach = (b_long is not None and b_short is not None
                          and b_long >= self.burn_threshold
                          and b_short >= self.burn_threshold)
                row = {"target": t.name, "objective": t.describe(),
                       "burn_long": b_long, "burn_short": b_short,
                       "window_long_s": self.long_s,
                       "window_short_s": self.short_s,
                       "threshold": self.burn_threshold,
                       "breaching": breach}
                out.append(row)
                if breach != self._breaching[t.name]:
                    self._breaching[t.name] = breach
                    kind = "slo_alert" if breach else "slo_clear"
                    event = {kind: dict(row), "ts": time.time()}
                    if breach:
                        self.alerts_total += 1
                    self.alerts.append(event)
                    events.append(event)
            self._last_eval = out
        for event in events:
            emit = getattr(self.metrics, "_emit", None)
            if emit is not None:
                emit(event)
            if self.on_alert is not None:
                self.on_alert(event)
        return out

    @property
    def breaching(self) -> bool:
        with self._lock:
            return any(self._breaching.values())

    # ---------------------------------------------------------- reporting
    def summary(self) -> dict:
        with self._lock:
            return {"targets": [t.name for t in self.targets],
                    "breaching": sorted(k for k, v in
                                        self._breaching.items() if v),
                    "alerts_total": self.alerts_total,
                    "last_eval": list(self._last_eval)}

    def metrics_text(self, prefix: str = "paddle_tpu_slo") -> str:
        """Burn gauges (labeled per target+window) + the alert counter,
        via the shared renderer — registry-composable like every other
        block."""
        with self._lock:
            last_eval = list(self._last_eval)
        lines: List[str] = []
        full = f"{prefix}_burn_rate" if prefix else "burn_rate"
        lines += [f"# HELP {full} SLO error-budget burn rate by target "
                  f"and window",
                  f"# TYPE {full} gauge"]
        for row in last_eval:
            for win, key in (("long", "burn_long"), ("short",
                                                     "burn_short")):
                v = row[key]
                if v is None:
                    continue
                v = v if v in (float("inf"),) else round(v, 6)
                lines.append(f'{full}{{target="{row["target"]}",'
                             f'window="{win}"}} {format_value(v)}')
        lines += gauge_lines(prefix, "breaching",
                             1 if self.breaching else 0,
                             "any SLO target currently in multi-window "
                             "breach")
        lines += counter_lines(prefix, "alerts_total", self.alerts_total,
                               "SLO burn-rate alerts fired (breach "
                               "transitions)")
        return "\n".join(lines) + "\n"

    def table(self) -> str:
        with self._lock:
            last_eval = list(self._last_eval)
        lines = [f"---- SLO burn (long {self.long_s:g}s / short "
                 f"{self.short_s:g}s, threshold "
                 f"{self.burn_threshold:g}) ----"]
        for row in last_eval:
            def fmt(v):
                return "n/a" if v is None else f"{v:8.2f}"
            lines.append(
                f"  {row['target']:<12} long {fmt(row['burn_long'])}  "
                f"short {fmt(row['burn_short'])}  "
                f"{'BREACH' if row['breaching'] else 'ok'} "
                f"({row['objective']})")
        return "\n".join(lines)
