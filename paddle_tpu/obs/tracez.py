"""TraceBuffer — bounded per-request trace retention with TAIL sampling.

A serving replica finishes thousands of requests per second; logging every
trace is the thing per-request JSONL is for (offline). `/tracez` answers a
different question — "show me why p99 was slow, NOW" — which head
sampling (keep 1-in-N) is structurally unable to answer: the traces that
explain a tail latency are, by definition, in the tail. This buffer
samples at the TAIL, after the request's outcome is known:

  - every non-`done` request (rejected / timeout / error) is retained —
    failures are always evidence;
  - every `done` request whose end-to-end latency lands at or above the
    `slow_quantile` (default p90: the slowest decile) of ALL latencies
    observed so far is retained — the quantile estimate derives from a
    log-bucket histogram over the full stream, so admission stays O(1)
    and the "slow" bar tracks the live distribution, not the buffer;
  - fast successes pass through a recency window (the newest ones stay
    until capacity pressure evicts them) so `/tracez` also shows what
    NORMAL looks like next to the outliers.

Eviction under a full buffer is priority-ordered: oldest fast-`done`
entry first, then oldest slow-`done`, then (only when the buffer is all
failures) the oldest failure. Capacity is a hard bound — the buffer can
never grow past it regardless of traffic shape.

Records are plain dicts (the `Request.record()` payload: status, span
stamps, window events, derived latencies, trace_id), so the buffer is
engine-agnostic and JSON-serializable as-is.
"""
from __future__ import annotations

import threading
from typing import List, Optional

from ..profiler._metrics import LogHistogram

__all__ = ["TraceBuffer", "chrome_trace"]


def chrome_trace(records) -> dict:
    """Render request trace records (TraceBuffer.snapshot() /
    Request.record() dicts) as Chrome trace-event JSON — the format
    ui.perfetto.dev and chrome://tracing load directly. One process per
    request (named by trace_id + status), two lanes: `request` carries
    the root span and the derived queue span, `engine` carries every
    engine-call window the request rode (prefill/decode/spec_verify
    chunks). Timestamps are microseconds relative to the earliest
    enqueue across the batch, so the view opens on a shared timeline."""
    out = []
    bases = []
    for rec in records:
        t = (rec.get("spans") or {}).get("t_enqueue")
        if t is not None:
            bases.append(float(t))
    t_base = min(bases) if bases else 0.0

    def us(t):
        return round((float(t) - t_base) * 1e6, 3)

    for i, rec in enumerate(records):
        pid = i + 1
        spans = rec.get("spans") or {}
        label = f"req {rec.get('trace_id') or rec.get('id')} " \
                f"[{rec.get('status')}]"
        out.append({"ph": "M", "pid": pid, "name": "process_name",
                    "args": {"name": label}})
        out.append({"ph": "M", "pid": pid, "tid": 0,
                    "name": "thread_name", "args": {"name": "request"}})
        out.append({"ph": "M", "pid": pid, "tid": 1,
                    "name": "thread_name", "args": {"name": "engine"}})
        t_enq = spans.get("t_enqueue")
        t_adm = spans.get("t_admit")
        t_fin = spans.get("t_finish")
        t_tok = spans.get("t_first_token")
        if t_enq is not None and t_fin is not None:
            args = {k: rec[k] for k in ("queue_s", "ttft_s", "tpot_s",
                                        "e2e_s", "reason") if k in rec}
            out.append({"ph": "X", "pid": pid, "tid": 0,
                        "name": "request",
                        "cat": rec.get("status") or "request",
                        "ts": us(t_enq),
                        "dur": round((t_fin - t_enq) * 1e6, 3),
                        "args": args})
        if t_enq is not None and t_adm is not None:
            out.append({"ph": "X", "pid": pid, "tid": 0, "name": "queue",
                        "cat": "queue", "ts": us(t_enq),
                        "dur": round((t_adm - t_enq) * 1e6, 3)})
        if t_tok is not None:
            out.append({"ph": "I", "pid": pid, "tid": 0,
                        "name": "first_token", "s": "t",
                        "ts": us(t_tok)})
        for ev in rec.get("events") or []:
            name, a, b = ev[0], ev[1], ev[2]
            out.append({"ph": "X", "pid": pid, "tid": 1, "name": name,
                        "cat": "engine", "ts": us(a),
                        "dur": round((b - a) * 1e6, 3)})
    return {"traceEvents": out, "displayTimeUnit": "ms"}


class TraceBuffer:
    """See module docstring. `capacity` bounds retained traces;
    `slow_quantile` sets the always-keep latency bar (0.9 = slowest
    decile). Thread-safe: the engine adds from its serving thread while
    the telemetry server snapshots from request-handler threads."""

    def __init__(self, capacity: int = 256, *, slow_quantile: float = 0.9,
                 hist_lo: float = 1e-4, hist_hi: float = 1e3,
                 per_decade: int = 20):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not (0.0 < slow_quantile < 1.0):
            raise ValueError(f"slow_quantile must be in (0, 1), "
                             f"got {slow_quantile}")
        self.capacity = int(capacity)
        self.slow_quantile = float(slow_quantile)
        self._hist = LogHistogram(lo=hist_lo, hi=hist_hi,
                                  per_decade=per_decade)
        self._entries: List[dict] = []          # insertion-ordered
        self._seq = 0
        self.seen = 0
        self.evicted = 0
        self._lock = threading.Lock()

    # ---------------------------------------------------------- admission
    def add(self, record: dict):
        """Admit one terminal request record. Classification happens here
        (tail sampling: the outcome is known), eviction keeps the bound."""
        status = record.get("status")
        e2e = record.get("e2e_s")
        with self._lock:
            self.seen += 1
            self._seq += 1
            slow = False
            if status == "done" and e2e is not None:
                # the bar BEFORE this observation joins the stream: the
                # first request is never "slow relative to itself"
                bar = self._hist.percentile(self.slow_quantile) \
                    if self._hist.count else None
                self._hist.observe(max(float(e2e), 0.0))
                slow = bar is not None and e2e >= bar
            entry = {"seq": self._seq, "slow": slow, "record": record}
            self._entries.append(entry)
            while len(self._entries) > self.capacity:
                self._evict_one()
        return self

    def _evict_one(self):
        """Oldest fast success first, then oldest slow success, then —
        only when everything retained is a failure — the oldest entry."""
        victim = None
        for e in self._entries:                 # oldest-first scan
            st = e["record"].get("status")
            if st == "done" and not e["slow"]:
                victim = e
                break
        if victim is None:
            for e in self._entries:
                if e["record"].get("status") == "done":
                    victim = e
                    break
        if victim is None:
            victim = self._entries[0]
        self._entries.remove(victim)
        self.evicted += 1

    # ---------------------------------------------------------- reporting
    def snapshot(self, *, limit: Optional[int] = None,
                 status: Optional[str] = None,
                 order: str = "recent") -> List[dict]:
        """Retained records, newest first (`order="recent"`) or slowest
        first (`order="slowest"` — the p99 post-mortem view); `status`
        filters on the record's terminal status."""
        if order not in ("recent", "slowest"):
            raise ValueError(f"order must be 'recent' or 'slowest', "
                             f"got {order!r}")
        with self._lock:
            entries = list(self._entries)
        if status is not None:
            entries = [e for e in entries
                       if e["record"].get("status") == status]
        if order == "slowest":
            entries.sort(key=lambda e: (
                -(e["record"].get("e2e_s") or 0.0), -e["seq"]))
        else:
            entries.sort(key=lambda e: -e["seq"])
        if limit is not None:
            entries = entries[:max(int(limit), 0)]
        return [dict(e["record"], _slow=e["slow"]) for e in entries]

    def summary(self) -> dict:
        with self._lock:
            by_status: dict = {}
            slow = 0
            for e in self._entries:
                st = e["record"].get("status") or "unknown"
                by_status[st] = by_status.get(st, 0) + 1
                slow += 1 if e["slow"] else 0
            return {"capacity": self.capacity,
                    "retained": len(self._entries),
                    "retained_slow": slow,
                    "by_status": by_status,
                    "seen": self.seen, "evicted": self.evicted,
                    "slow_quantile": self.slow_quantile,
                    "slow_bar_s": self._hist.percentile(
                        self.slow_quantile)}

    def clear(self):
        with self._lock:
            self._entries.clear()
        return self
