"""MemoryLedger — owner-attributed device-memory accounting (ISSUE 18).

The obs stack can say where the TIME went (goodput timeline, collective
ledger, flight recorder) but not where the HBM went — and for the
north-star workload (heavy serving + 2.7B/6.7B training) allocation
failure is the dominant production outage, surfaced only as an opaque
XLA RESOURCE_EXHAUSTED. This module closes that gap with the same
conservation discipline the goodput timeline uses for wall time:

  owners        every live device byte belongs to a REGISTERED owner —
                model params, optimizer state, KV block pools (per
                engine, reserved at allocator granularity), prefix-cache
                retained blocks (an OVERLAY: those blocks live inside
                the pool's reservation, so they are reported but never
                double-counted in the conservation sum), in-flight
                checkpoint snapshots and the host-RAM spill tier (host
                owners: tracked separately, never summed against HBM).
  conservation  `census()` reconciles the attributed sum against
                ``device.memory_allocated()``: attributed + unattributed
                ≡ allocator view, by construction — the ledger cannot
                silently lose bytes, it can only grow `unattributed`,
                which is itself the "go find the missing owner" signal.
  never sync    a ledger read touches HOST counters only. Owners are
                zero-arg readers over accounting the engine already
                keeps (``pool.used_blocks * bytes_per_block``, a numpy
                snapshot's ``nbytes``) — pinned like every other scrape:
                /memz cannot trigger a compile or a device sync. (On
                allocator-less host platforms the reconciliation view
                ``memory_allocated()`` walks jax.live_arrays() METADATA
                — sizes, never values — so even that path never syncs.)
  deltas        every owner change appends one row to a bounded delta
                ring: the growth curve that turns "OOM at step 40312"
                into "the prefix cache grew 9 GiB over the last hour".
  forensics     `post_mortem()` dumps the full census + the last N
                delta rows + the offending request/step to a structured
                JSONL artifact (rendered by ``tools/oom_report.py``);
                the serving step loop and the TrainStep launch sites
                call it when an allocation failure unwinds through them
                (`looks_like_oom`). `check_headroom()` emits one
                structured ``{"headroom_low"}`` row per episode — a
                flight-recorder trigger key, so the profiler capture is
                pinned BEFORE the OOM, not requested after it.

Exposure: ``/memz`` (TelemetryServer route handler `memz()`, merged
fleet-wide by ``FleetAggregator.fleet_memz`` with per-replica labels),
registry gauges ``hbm_bytes{owner=...}`` / ``hbm_headroom_bytes``
(`metrics_text()`), and a `/statusz` memory block (`statusz_block()`).
"""
from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

_logger = logging.getLogger("paddle_tpu.obs.memz")

__all__ = ["MemoryLedger", "looks_like_oom", "load_postmortem",
           "render_report"]

# substrings that identify a device-allocator failure in the zoo of
# exception types XLA/jaxlib raise it as (RuntimeError, XlaRuntimeError,
# jaxlib.xla_extension.* — matching the TEXT is the stable contract)
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "oom", "failed to allocate",
                "allocation failure")


def looks_like_oom(exc: BaseException) -> bool:
    """Is this exception a device allocation failure? MemoryError always;
    anything else by the RESOURCE_EXHAUSTED / out-of-memory markers in
    its text — the serving/train launch wrappers gate the post-mortem
    dump on this so an ordinary bug does not masquerade as an OOM."""
    if isinstance(exc, MemoryError):
        return True
    text = f"{type(exc).__name__}: {exc}".lower()
    return any(m in text for m in _OOM_MARKERS)


class _Owner:
    __slots__ = ("name", "kind", "device", "overlay", "reader",
                 "bytes", "high", "meta", "detail")

    def __init__(self, name, kind, device, overlay, reader, meta):
        self.name = name
        self.kind = kind
        self.device = device        # counts toward the HBM conservation sum
        self.overlay = overlay      # bytes live INSIDE another owner's
        #                             reservation: reported, never summed
        self.reader = reader
        self.bytes = 0
        self.high = 0               # high-watermark since registration
        self.meta = dict(meta or {})
        self.detail: Dict = {}

    def to_dict(self) -> dict:
        out = {"owner": self.name, "kind": self.kind,
               "bytes": self.bytes, "high_watermark_bytes": self.high,
               "device": self.device}
        if self.overlay:
            out["overlay"] = True
        if self.meta:
            out["meta"] = self.meta
        if self.detail:
            out["detail"] = self.detail
        return out


class MemoryLedger:
    """See module docstring.

        ledger = MemoryLedger()
        ledger.register("kv_pool", lambda: pool.num_blocks * bpb,
                        kind="kv")
        ledger.set("ckpt_inflight", nbytes, kind="checkpoint",
                   device=False)
        ledger.census()      # owner table + unattributed residual
        ledger.memz({})      # the /memz route payload

    `allocated_fn` / `capacity_fn` inject the allocator view (tests,
    deterministic smokes); defaults read ``paddle_tpu.device`` lazily and
    degrade to None when no view exists (census still renders — the
    conservation columns just stay null). `headroom_low_frac`: headroom
    below this fraction of capacity emits one ``{"headroom_low"}`` row
    per episode through `on_row`/`jsonl_path` (the flight-recorder
    trigger); recovery emits the inert ``{"headroom_low_clear"}`` twin.
    """

    def __init__(self, *, capacity_bytes: Optional[int] = None,
                 allocated_fn: Optional[Callable[[], Optional[int]]] = None,
                 delta_ring: int = 256,
                 headroom_low_frac: float = 0.10,
                 jsonl_path: Optional[str] = None,
                 on_row: Optional[Callable[[dict], None]] = None,
                 postmortem_dir: Optional[str] = None):
        if int(delta_ring) < 1:
            raise ValueError(f"delta_ring must be >= 1, got {delta_ring}")
        self.capacity_bytes = capacity_bytes
        self._allocated_fn = allocated_fn
        self.headroom_low_frac = float(headroom_low_frac)
        self.jsonl_path = jsonl_path
        self.on_row = on_row
        self.postmortem_dir = postmortem_dir
        self._lock = threading.RLock()
        self._owners: Dict[str, _Owner] = {}
        self._deltas: deque = deque(maxlen=int(delta_ring))
        self._attr_high = 0        # high-watermark of the attributed sum
        self._headroom_low = False  # episode state (one row per episode)
        self._pm_seq = 0
        self.samples_total = 0
        self.postmortems_total = 0
        self.headroom_low_total = 0

    # ------------------------------------------------------------- owners
    def register(self, name: str,
                 reader: Optional[Callable[[], object]] = None, *,
                 kind: str = "other", device: bool = True,
                 overlay: bool = False, meta: Optional[dict] = None,
                 replace: bool = False) -> "MemoryLedger":
        """Register one owner. `reader` is a ZERO-ARG host-side callable
        returning the owner's current bytes (int, or a dict with a
        "bytes" key whose other entries become the owner's `detail`) —
        it must never touch device state. Reader-less owners are updated
        by `set()`/`add()` pushes instead. Registering an existing name
        raises unless `replace=True` (an engine rebuilding its pools
        replaces deliberately; two subsystems colliding is a bug)."""
        with self._lock:
            if name in self._owners and not replace:
                raise ValueError(f"memory owner {name!r} already "
                                 f"registered (replace=True to rebind)")
            self._owners[name] = _Owner(name, kind, bool(device),
                                        bool(overlay), reader, meta)
        if reader is not None:
            self.sample(name)
        return self

    def unregister(self, name: str) -> bool:
        with self._lock:
            return self._owners.pop(name, None) is not None

    @property
    def owners(self) -> List[str]:
        with self._lock:
            return list(self._owners)

    def _apply(self, o: _Owner, nbytes: int, now: float):
        nbytes = max(int(nbytes), 0)
        if nbytes == o.bytes:
            return
        self._deltas.append({"ts": now, "owner": o.name,
                             "bytes": nbytes,
                             "delta": nbytes - o.bytes})
        o.bytes = nbytes
        o.high = max(o.high, nbytes)
        if o.device and not o.overlay:
            attr = sum(w.bytes for w in self._owners.values()
                       if w.device and not w.overlay)
            self._attr_high = max(self._attr_high, attr)

    def set(self, name: str, nbytes: int, *, kind: str = "other",
            device: bool = True, overlay: bool = False,
            meta: Optional[dict] = None) -> "MemoryLedger":
        """Push-update one owner's bytes (auto-registers a reader-less
        owner on first set — the checkpoint manager's in-flight snapshot
        comes and goes without ceremony)."""
        now = time.time()
        with self._lock:
            o = self._owners.get(name)
            if o is None:
                o = _Owner(name, kind, bool(device), bool(overlay),
                           None, meta)
                self._owners[name] = o
            self._apply(o, nbytes, now)
        return self

    def add(self, name: str, delta: int, **kw) -> "MemoryLedger":
        with self._lock:
            cur = self._owners[name].bytes if name in self._owners else 0
        return self.set(name, cur + int(delta), **kw)

    def sample(self, *names: str) -> "MemoryLedger":
        """Pull every reader-backed owner (or just `names`): host-side
        arithmetic over counters the engine already keeps — cheap enough
        to ride every BlockPool alloc/free (`pool.on_change`)."""
        now = time.time()
        with self._lock:
            self.samples_total += 1
            targets = [self._owners[n] for n in names
                       if n in self._owners] if names \
                else list(self._owners.values())
            for o in targets:
                if o.reader is None:
                    continue
                try:
                    val = o.reader()
                except Exception as e:      # noqa: BLE001 — a broken
                    # reader must not take the scrape (or an alloc
                    # path!) down; the stale value + the log are the
                    # degraded-but-visible behavior
                    _logger.warning("memz reader %r failed: %s",
                                    o.name, e)
                    continue
                if isinstance(val, dict):
                    nbytes = int(val.get("bytes", 0))
                    o.detail = {k: v for k, v in val.items()
                                if k != "bytes"}
                else:
                    nbytes = int(val)
                self._apply(o, nbytes, now)
        return self

    # ------------------------------------------------------------- census
    def _allocated(self) -> Optional[int]:
        if self._allocated_fn is not None:
            try:
                v = self._allocated_fn()
                return None if v is None else int(v)
            except Exception:
                return None
        try:
            from ..device import memory_allocated
            return int(memory_allocated())
        except Exception:
            return None

    def _capacity(self) -> Optional[int]:
        if self.capacity_bytes is not None:
            return int(self.capacity_bytes)
        try:
            from ..device import has_allocator_stats, memory_stats
            if not has_allocator_stats():
                return None            # live-array fallback has no limit
            limit = memory_stats().get("bytes_limit")
            return int(limit) if limit else None
        except Exception:
            return None

    def attributed_bytes(self) -> int:
        """Sum of device owners (overlays excluded — their bytes already
        live inside another owner's reservation)."""
        with self._lock:
            return sum(o.bytes for o in self._owners.values()
                       if o.device and not o.overlay)

    def quick_stats(self) -> dict:
        """The StepMonitor's per-record memory sample when a ledger is
        attached (ISSUE 18 satellite): host counters only — the
        live-array scan stays the RECONCILIATION path (census), never
        the per-step one."""
        with self._lock:
            attr = sum(o.bytes for o in self._owners.values()
                       if o.device and not o.overlay)
            return {"bytes_in_use": attr,
                    "peak_bytes_in_use": max(self._attr_high, attr),
                    "source": "memz_ledger"}

    def top_owners(self, n: int = 3) -> List[dict]:
        """Largest device owners — the "who to evict" list the kv_oom
        reject reason carries."""
        with self._lock:
            owners = sorted((o for o in self._owners.values()
                             if o.device and not o.overlay),
                            key=lambda o: -o.bytes)
            return [{"owner": o.name, "bytes": o.bytes}
                    for o in owners[:max(int(n), 0)] if o.bytes > 0]

    def census(self, *, reconcile: bool = True) -> dict:
        """The full owner table + the conservation columns. Samples every
        reader first; `reconcile=False` skips the allocator view (pure
        owner table — the per-alloc hot path never wants the live-array
        walk)."""
        self.sample()
        allocated = self._allocated() if reconcile else None
        capacity = self._capacity() if reconcile else None
        with self._lock:
            device = [o.to_dict() for o in self._owners.values()
                      if o.device]
            host = [o.to_dict() for o in self._owners.values()
                    if not o.device]
            attributed = sum(o.bytes for o in self._owners.values()
                             if o.device and not o.overlay)
            attr_high = max(self._attr_high, attributed)
        device.sort(key=lambda d: -d["bytes"])
        host.sort(key=lambda d: -d["bytes"])
        out = {"ts": time.time(),
               "owners": device, "host_owners": host,
               "attributed_bytes": attributed,
               "attributed_high_watermark_bytes": attr_high,
               "allocated_bytes": allocated,
               "unattributed_bytes": (allocated - attributed
                                      if allocated is not None else None),
               "capacity_bytes": capacity,
               "headroom_bytes": (capacity - allocated
                                  if capacity is not None
                                  and allocated is not None else None)}
        if capacity:
            for row in out["owners"]:
                row["pct_of_hbm"] = round(100.0 * row["bytes"]
                                          / capacity, 2)
            if allocated is not None:
                out["headroom_frac"] = round(
                    out["headroom_bytes"] / capacity, 4)
        try:
            from ..device import has_allocator_stats
            out["source"] = "allocator" if has_allocator_stats() \
                else "live_arrays"
        except Exception:
            out["source"] = None
        return out

    def deltas(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            rows = list(self._deltas)
        return rows if n is None else rows[-max(int(n), 0):]

    # ----------------------------------------------------------- headroom
    def check_headroom(self, census: Optional[dict] = None
                       ) -> Optional[dict]:
        """Evaluate the headroom-low episode state; returns the emitted
        row (entry or clear transition) or None. The entry row carries a
        ``headroom_low`` key — a flight-recorder trigger, so the capture
        is pinned BEFORE the OOM; the clear row's key is inert by the
        *_clear convention."""
        c = census if census is not None else self.census()
        headroom, capacity = c.get("headroom_bytes"), c.get(
            "capacity_bytes")
        if headroom is None or not capacity:
            return None
        low = headroom < self.headroom_low_frac * capacity
        with self._lock:
            if low == self._headroom_low:
                return None
            self._headroom_low = low
            if low:
                self.headroom_low_total += 1
        body = {"headroom_bytes": headroom, "capacity_bytes": capacity,
                "headroom_frac": round(headroom / capacity, 4),
                "threshold_frac": self.headroom_low_frac,
                "top_owners": self.top_owners(3)}
        key = "headroom_low" if low else "headroom_low_clear"
        return self._emit({key: body, "ts": time.time()})

    def _emit(self, row: dict) -> dict:
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if self.on_row is not None:
            self.on_row(row)
        return row

    # ------------------------------------------------------------ surface
    def memz(self, query: Optional[dict] = None) -> dict:
        """TelemetryServer route handler for /memz: the census table,
        the last ``?deltas=N`` owner-delta rows (default 32) and the
        headroom state. Evaluates the headroom trigger as a side effect
        — every scrape is also an arming opportunity."""
        q = query or {}
        try:
            n_deltas = int(q.get("deltas", 32))
        except (TypeError, ValueError):
            raise ValueError(f"deltas must be an integer, "
                             f"got {q.get('deltas')!r}")
        c = self.census()
        self.check_headroom(c)
        return {**c, "deltas": self.deltas(n_deltas),
                "headroom_low": self._headroom_low,
                "postmortems_total": self.postmortems_total}

    def statusz_block(self) -> dict:
        """The compact /statusz memory block: one line per owner +
        conservation summary (the full table is /memz's job)."""
        c = self.census()
        return {"owners": {d["owner"]: d["bytes"] for d in c["owners"]},
                "host_owners": {d["owner"]: d["bytes"]
                                for d in c["host_owners"]},
                "attributed_bytes": c["attributed_bytes"],
                "allocated_bytes": c["allocated_bytes"],
                "unattributed_bytes": c["unattributed_bytes"],
                "headroom_bytes": c["headroom_bytes"],
                "headroom_low": self._headroom_low}

    def metrics_text(self, prefix: str = "paddle_tpu") -> str:
        """Registry producer: ``hbm_bytes{owner=...}`` (device owners,
        overlays included — they carry their own label and gauges are
        never summed by the fleet merge), per-owner high watermarks,
        ``host_bytes{owner=...}`` for the host tier, and the scalar
        conservation/headroom gauges the SLO machinery consumes."""
        from ..profiler._metrics import (counter_lines, gauge_lines,
                                         labeled_gauge_lines)
        c = self.census()
        lines: List[str] = []
        lines += labeled_gauge_lines(
            prefix, "hbm_bytes", "owner",
            [(d["owner"], d["bytes"]) for d in c["owners"]],
            "live device bytes attributed to each registered owner")
        lines += labeled_gauge_lines(
            prefix, "hbm_high_watermark_bytes", "owner",
            [(d["owner"], d["high_watermark_bytes"])
             for d in c["owners"]],
            "per-owner high watermark since registration")
        lines += labeled_gauge_lines(
            prefix, "host_bytes", "owner",
            [(d["owner"], d["bytes"]) for d in c["host_owners"]],
            "host-RAM bytes attributed to each host-tier owner")
        lines += gauge_lines(prefix, "hbm_attributed_bytes",
                             c["attributed_bytes"],
                             "sum of device owners (overlays excluded)")
        lines += gauge_lines(prefix, "hbm_allocated_bytes",
                             c["allocated_bytes"],
                             "allocator view the ledger reconciles "
                             "against")
        lines += gauge_lines(prefix, "hbm_unattributed_bytes",
                             c["unattributed_bytes"],
                             "allocator bytes no registered owner "
                             "claims")
        lines += gauge_lines(prefix, "hbm_headroom_bytes",
                             c["headroom_bytes"],
                             "capacity minus allocated — the admission/"
                             "flight-recorder arming signal")
        lines += counter_lines(prefix, "hbm_headroom_low_total",
                               self.headroom_low_total,
                               "headroom-low episodes entered")
        lines += counter_lines(prefix, "hbm_postmortems_total",
                               self.postmortems_total,
                               "OOM post-mortem artifacts written")
        return "\n".join(lines) + "\n" if lines else ""

    # ---------------------------------------------------------- forensics
    def post_mortem(self, *, error: Optional[BaseException] = None,
                    context: Optional[dict] = None,
                    dir: Optional[str] = None,
                    deltas: int = 64) -> Optional[str]:
        """Dump the OOM forensics artifact: one JSONL file holding the
        full census (headed by the largest owner — the one-line answer),
        the last `deltas` owner-delta rows (the growth curve) and the
        offending request/step context. Returns the artifact path, or
        None when it could not be written — the dump rides an exception
        handler and must never mask the original failure."""
        out_dir = dir or self.postmortem_dir or "oom_postmortem"
        try:
            os.makedirs(out_dir, exist_ok=True)
            c = self.census()
            top = c["owners"][0] if c["owners"] else None
            with self._lock:
                self._pm_seq += 1
                seq = self._pm_seq
            path = os.path.join(
                out_dir, f"oom_{os.getpid()}_{seq:03d}.jsonl")
            head = {"oom": {
                "ts": time.time(),
                "error": (f"{type(error).__name__}: {error}"
                          if error is not None else None),
                "is_alloc_failure": (looks_like_oom(error)
                                     if error is not None else None),
                "context": context or {},
                "largest_owner": top["owner"] if top else None,
                "largest_owner_bytes": top["bytes"] if top else None}}
            with open(path, "w") as f:
                f.write(json.dumps(head) + "\n")
                f.write(json.dumps({"census": c}) + "\n")
                for d in self.deltas(deltas):
                    f.write(json.dumps({"delta": d}) + "\n")
            with self._lock:
                self.postmortems_total += 1
            _logger.error("memz: OOM post-mortem written to %s "
                          "(largest owner: %s)", path,
                          top["owner"] if top else "<none>")
            return path
        except Exception as e:          # noqa: BLE001 — see docstring
            _logger.warning("memz: post-mortem dump failed: %s", e)
            return None


# ------------------------------------------------------------- rendering

def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TiB"


def load_postmortem(path: str) -> dict:
    """Parse one post-mortem artifact back into
    {"oom": ..., "census": ..., "deltas": [...]}. Raises ValueError on a
    file that is not a memz artifact."""
    oom = census = None
    deltas: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            row = json.loads(line)
            if "oom" in row:
                oom = row["oom"]
            elif "census" in row:
                census = row["census"]
            elif "delta" in row:
                deltas.append(row["delta"])
    if oom is None or census is None:
        raise ValueError(f"{path} is not a memz post-mortem artifact "
                         f"(missing oom/census rows)")
    return {"oom": oom, "census": census, "deltas": deltas}


def render_report(path: str) -> str:
    """Human rendering of one artifact (tools/oom_report.py): the
    headline (largest owner + error), the owner table with bytes / % of
    HBM / high watermarks, the host tier, and each owner's recent growth
    from the delta rows."""
    pm = load_postmortem(path)
    oom, census, deltas = pm["oom"], pm["census"], pm["deltas"]
    lines = ["OOM post-mortem", "=" * 60]
    if oom.get("error"):
        lines.append(f"error:   {oom['error']}")
    if oom.get("largest_owner"):
        lines.append(f"largest owner: {oom['largest_owner']} "
                     f"({_fmt_bytes(oom.get('largest_owner_bytes'))})")
    ctx = oom.get("context") or {}
    if ctx:
        lines.append("context: " + ", ".join(
            f"{k}={v}" for k, v in sorted(ctx.items())))
    lines.append("")
    lines.append(f"{'owner':<24}{'bytes':>12}{'% HBM':>8}{'high':>12}")
    lines.append("-" * 60)
    for d in census.get("owners", []):
        pct = d.get("pct_of_hbm")
        lines.append(
            f"{d['owner'][:23]:<24}{_fmt_bytes(d['bytes']):>12}"
            f"{(f'{pct:.1f}' if pct is not None else '-'):>8}"
            f"{_fmt_bytes(d.get('high_watermark_bytes')):>12}")
    lines.append("-" * 60)
    lines.append(f"{'attributed':<24}"
                 f"{_fmt_bytes(census.get('attributed_bytes')):>12}")
    lines.append(f"{'allocated':<24}"
                 f"{_fmt_bytes(census.get('allocated_bytes')):>12}")
    lines.append(f"{'unattributed':<24}"
                 f"{_fmt_bytes(census.get('unattributed_bytes')):>12}")
    lines.append(f"{'headroom':<24}"
                 f"{_fmt_bytes(census.get('headroom_bytes')):>12}")
    hosts = census.get("host_owners", [])
    if hosts:
        lines.append("")
        lines.append("host tier:")
        for d in hosts:
            lines.append(f"  {d['owner'][:22]:<24}"
                         f"{_fmt_bytes(d['bytes']):>12}")
    if deltas:
        lines.append("")
        lines.append(f"growth curve (last {len(deltas)} owner deltas):")
        for d in deltas:
            sign = "+" if d["delta"] >= 0 else ""
            step = f"{sign}{_fmt_bytes(d['delta'])}"
            lines.append(f"  {d['owner'][:22]:<24}{step:>12}  "
                         f"-> {_fmt_bytes(d['bytes'])}")
    return "\n".join(lines)
