"""paddle_tpu.obs — the fleet's sensory layer (ISSUE 12).

Everything the repo measures (serving request metrics, step telemetry,
goodput attribution, prefix-cache/spec counters) was reachable only by
in-process Python calls; before a router or autoscaler can act on a
replica, the replica needs an ops surface over the wire. This package is
that surface, stdlib-only:

  MetricsRegistry    composes every exposition producer into ONE
                     collision-checked, lint-clean Prometheus page
                     (registry.py; the promtool-style `lint_exposition`
                     rides the shared profiler/_metrics parser).
  TelemetryServer    threaded HTTP server: /metrics, /healthz (drain +
                     queue depth + overloaded_total — the autoscaler
                     inputs), /statusz (config/occupancy snapshot),
                     /tracez (server.py).
  TraceBuffer        bounded per-request trace retention with TAIL
                     sampling: every failure + the slowest decile always
                     kept (tracez.py).
  SLOMonitor         declarative TTFT/TPOT/e2e/goodput objectives
                     evaluated as multi-window burn rates over the
                     existing log-bucket histograms, alerting through
                     the structured JSONL path (slo.py; `parse_slo` /
                     `evaluate_slo` back the serve_bench --slo gate).

Fleet scope (ISSUE 13) — one replica's surface is not a fleet's:

  FleetAggregator    scrapes N TelemetryServers into ONE merged,
                     lint-clean page (counters summed, gauges labeled
                     {replica=...}, histograms pooled bucket-wise) plus
                     the /fleet/healthz roll-up and the trace_id-merged
                     /fleet/tracez; a dead member goes stale and is
                     degraded around, never a scrape 500 (fleet.py).
  CollectiveLedger   per-collective comm attribution (bytes, bus
                     bandwidth, exposed-vs-overlapped time) from a
                     captured trace — the decomposition of the r13
                     overlap_ratio gauge — plus shard-wall stitching for
                     the StepMonitor straggler gauges (collectives.py).

Flight-recorder scope (ISSUE 17) — alerts that die as JSONL rows can't
explain a regression:

  FlightRecorder     a bounded ring of profiler captures — periodic
                     low-duty-cycle background captures plus captures
                     PINNED by the trigger bus (SLO alerts, straggler
                     transitions, recompiles, numerics events), with an
                     eviction policy that never drops pinned evidence
                     before periodic baseline, a cooldown so an alert
                     storm yields ONE capture, and the live `/profilez`
                     route (list captures / render KernelView tables /
                     download the raw trace) merged fleet-wide like
                     tracez (flightrec.py; `tools/perf_diff.py` diffs
                     two captures at kernel granularity).

HBM-ledger scope (ISSUE 18) — where the time went was answerable, where
the HBM went was not:

  MemoryLedger       owner-attributed device-memory accounting (model
                     params, optimizer state, KV pools, prefix-cache
                     overlays, spill/checkpoint host tiers) reconciled
                     against `device.memory_allocated()` — attributed +
                     unattributed ≡ the allocator view, host counters
                     only (a /memz read never syncs). Exposes the /memz
                     route (fleet-merged by FleetAggregator.fleet_memz),
                     hbm_bytes{owner=...}/hbm_headroom_bytes gauges, a
                     headroom-low flight-recorder trigger, and the OOM
                     post-mortem artifact tools/oom_report.py renders
                     (memz.py).

Active-probing scope (ISSUE 19) — everything above is passive; none of
it can see a replica serving WRONG answers at perfect latency:

  Prober             golden-canary correctness sentinels: synthetic
                     requests through the REAL serving path (paged
                     admission, prefix hit/miss, spec decode), output
                     asserted BITWISE equal to goldens minted once per
                     config fingerprint via generate_static_ragged.
                     Tagged end-to-end out of user-facing SLO/goodput
                     accounting; failures are structured {"probe_fail"}
                     rows (flight-recorder trigger + memz census) and a
                     `failing` /probez state the FleetRouter ejects on
                     (probez.py; fleet-merged by fleet_probez with
                     config-drift detection).
  InvariantAuditor   deep host-side audits on the poller cadence:
                     BlockPool conservation, per-owner rows ≅ refcounts,
                     radix-trie ↔ pool cross-check, int8 scale
                     co-residency — invariant_* gauges + structured
                     findings on violation (probez.py).

`ServingEngine.serve_telemetry()` wires all of these around a live
engine (and owns the SLO burn-rate poll cadence via `poll_interval=`);
`hapi.callbacks.ProfilerCallback(telemetry=...)` exports a TRAINING
loop's StepMonitor + live goodput gauges through the same server.
"""
from .collectives import (CollectiveLedger, feed_shard_walls,  # noqa: F401
                          load_shard_walls)
from .fleet import (FleetAggregator, FleetMergeError,  # noqa: F401
                    bucket_percentile, merge_exposition)
from .flightrec import (FixtureBackend, FlightRecorder,  # noqa: F401
                        JaxProfilerBackend)
from .memz import MemoryLedger, looks_like_oom  # noqa: F401
from .probez import (GoldenStore, InvariantAuditor, Prober,  # noqa: F401
                     config_fingerprint)
from .registry import (ExpositionError, MetricsCollisionError,  # noqa: F401
                       MetricsRegistry, lint_exposition)
from .server import Raw, TelemetryServer  # noqa: F401
from .slo import (SLOMonitor, SLOTarget, evaluate_slo,  # noqa: F401
                  format_slo_table, parse_slo)
from .tracez import TraceBuffer, chrome_trace  # noqa: F401

__all__ = ["ExpositionError", "MetricsCollisionError", "MetricsRegistry",
           "lint_exposition", "TelemetryServer", "Raw", "SLOMonitor",
           "SLOTarget", "parse_slo", "evaluate_slo", "format_slo_table",
           "TraceBuffer", "chrome_trace", "FleetAggregator",
           "FleetMergeError", "merge_exposition", "bucket_percentile",
           "CollectiveLedger", "load_shard_walls", "feed_shard_walls",
           "FlightRecorder", "JaxProfilerBackend", "FixtureBackend",
           "MemoryLedger", "looks_like_oom", "Prober", "GoldenStore",
           "InvariantAuditor", "config_fingerprint"]
