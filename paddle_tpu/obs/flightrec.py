"""FlightRecorder — anomaly-triggered profiling with a bounded capture ring.

Every alerting signal the repo emits (SLO burn-rate alerts, straggler
transitions, numerics events, recompiles) dies as a JSONL row; by the
time a human attaches the Profiler the anomaly is gone. The flight
recorder closes that gap the way a production stack does: the profiler
is ALWAYS armed, captures are cheap and bounded, and the anomaly itself
pulls the trigger.

  ring        a bounded ring of capture records. Two kinds: `periodic`
              low-duty-cycle background captures (N steps every M steps,
              `every=0` disables) and `trigger` captures pinned by an
              anomaly. Eviction under ring pressure NEVER removes a
              pinned capture while a periodic one remains; only a ring
              full of pinned captures evicts its oldest pinned entry
              (capacity is a hard bound either way). Evicted captures
              drop their trace file from disk.
  trigger bus `attach(monitor=..., slo=..., metrics=...)` chains onto
              the existing structured-row hooks (StepMonitor.on_report,
              SLOMonitor.on_alert, ServingMetrics.on_record — previous
              hooks are preserved and restored by `detach()`) and sniffs
              rows for `slo_alert` / `straggler` / `recompile` /
              `numerics`-with-events. A matching row requests capture of
              the NEXT `trigger_steps` steps. Dedup is two-layer: a
              trigger while a capture is pending/active COALESCES into
              it (and pins it), and a trigger within `cooldown_s` of the
              last trigger-started capture is SUPPRESSED — an alert
              storm yields ONE capture.
  steps       the recorder is step-hook driven: `begin_step()` /
              `end_step()` (StepMonitor calls them when the recorder is
              attached) start the backend trace at the next step
              boundary and stop it `steps` later. Triggers from any
              thread only flip state under a lock; jax.profiler is ever
              touched from the step thread — a poller thread can never
              race the device tracer.
  evidence    every finished capture appends one structured
              `{"capture": ...}` JSONL row (when `jsonl_path` is set)
              linking trigger kind -> trace path -> the trigger's own
              row verbatim, and lands in the ring for `/profilez`
              (`profilez()` is a TelemetryServer route handler: list
              captures, render KernelView/DeviceView/DistributedView
              tables from a capture's trace, download the raw
              trace.json.gz).

The capture backend is injectable: `JaxProfilerBackend` (default) drives
`jax.profiler.start_trace/stop_trace`; `FixtureBackend` materializes a
checked-in trace file instead (CPU CI captures carry no device lanes, so
deterministic tests and the tier-1 smoke pin the analysis path with the
`mini_step` fixture). A failing backend counts `capture_errors` and the
recorder re-arms — profiling must never take the job down.
"""
from __future__ import annotations

import gzip
import json
import logging
import os
import shutil
import tempfile
import threading
import time
from typing import Callable, List, Optional

_logger = logging.getLogger("paddle_tpu.obs.flightrec")

__all__ = ["FlightRecorder", "JaxProfilerBackend", "FixtureBackend",
           "TRIGGER_KEYS"]

# structured-row keys the trigger bus fires on (transition rows only:
# *_clear rows carry different keys and stay inert). mem_pressure /
# headroom_low (ISSUE 18): the ledger's episode-entry rows arm a pinned
# capture BEFORE the OOM the episode is foreshadowing. probe_fail /
# invariant_violation (ISSUE 19): a correctness sentinel tripping pins
# the capture at the moment of divergence — silent-wrong-answer
# forensics, the one failure class latency telemetry can never see
TRIGGER_KEYS = ("slo_alert", "straggler", "recompile",
                "mem_pressure", "headroom_low", "probe_fail",
                "invariant_violation")


class JaxProfilerBackend:
    """Default capture backend: the real jax device tracer. `start()`
    opens a trace into a private temp dir; `stop(dest)` closes it, moves
    the newest trace file to `dest` and cleans the temp dir. Returns the
    dest path, or None when the tracer produced no file (timer-only
    platforms)."""

    def __init__(self):
        self._tmp: Optional[str] = None

    def start(self):
        import jax
        self._tmp = tempfile.mkdtemp(prefix="paddle-tpu-flightrec-")
        try:
            jax.profiler.start_trace(self._tmp)
        except Exception:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._tmp = None
            raise

    def stop(self, dest: str) -> Optional[str]:
        import jax
        from ..profiler.trace_analysis import find_trace_file
        tmp, self._tmp = self._tmp, None
        if tmp is None:
            return None
        try:
            jax.profiler.stop_trace()
            src = find_trace_file(tmp)
            if src is None:
                return None
            shutil.move(src, dest)
            return dest
        finally:
            shutil.rmtree(tmp, ignore_errors=True)


class FixtureBackend:
    """Capture backend that 'captures' a checked-in trace file: stop()
    copies `src` to the destination (gzipping .json -> .json.gz when
    needed). Gives tests and the CPU tier-1 smoke a deterministic,
    non-empty KernelView — a CPU jax capture has no device lanes."""

    def __init__(self, src: str):
        self.src = src
        self.captures = 0

    def start(self):
        pass

    def stop(self, dest: str) -> Optional[str]:
        self.captures += 1
        if self.src.endswith(".gz") or not dest.endswith(".gz"):
            shutil.copyfile(self.src, dest)
        else:
            with open(self.src, "rb") as f, gzip.open(dest, "wb") as g:
                shutil.copyfileobj(f, g)
        return dest


class FlightRecorder:
    """See module docstring.

        rec = FlightRecorder("run/flightrec", ring=8, every=200,
                             capture_steps=3, cooldown_s=60)
        rec.attach(monitor=monitor, slo=slo)     # the trigger bus
        ... monitor.begin_step()/end_step() drive it per step ...
        rec.profilez({})                         # the /profilez payload

    `every=0` (default) disables periodic captures — trigger-only.
    `trigger_steps` defaults to `capture_steps`. `clock` is the cooldown
    clock (monotonic seconds; injectable for tests)."""

    def __init__(self, dir: str, *, ring: int = 8, every: int = 0,
                 capture_steps: int = 2,
                 trigger_steps: Optional[int] = None,
                 cooldown_s: float = 30.0,
                 backend=None, jsonl_path: Optional[str] = None,
                 on_capture: Optional[Callable[[dict], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if int(ring) < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        if int(every) < 0:
            raise ValueError(f"every must be >= 0, got {every}")
        self.dir = os.path.abspath(dir)
        os.makedirs(self.dir, exist_ok=True)
        self.ring = int(ring)
        self.every = int(every)
        self.capture_steps = max(1, int(capture_steps))
        self.trigger_steps = max(1, int(trigger_steps
                                        if trigger_steps is not None
                                        else capture_steps))
        self.cooldown_s = float(cooldown_s)
        self.backend = backend if backend is not None \
            else JaxProfilerBackend()
        self.jsonl_path = jsonl_path
        self.on_capture = on_capture
        self._clock = clock
        self._lock = threading.RLock()
        self.captures: List[dict] = []      # the ring, oldest..newest
        self._seq = 0
        self._step = 0                      # steps seen (begin_step count)
        self._pending: Optional[dict] = None  # requested, tracer not on
        self._active: Optional[dict] = None   # tracer running
        # first periodic capture starts at the first step, then every
        # `every` steps from each periodic start
        self._next_periodic = 1
        self._last_trigger_t: Optional[float] = None
        self.triggers_total = 0
        self.triggers_coalesced = 0
        self.triggers_suppressed = 0
        self.captures_total = 0
        self.captures_pinned_total = 0
        self.capture_errors = 0
        self.evicted_periodic = 0
        self.evicted_pinned = 0
        self._attached: List[tuple] = []

    # ------------------------------------------------------------ triggers
    def trigger(self, kind: str, row: Optional[dict] = None
                ) -> Optional[str]:
        """Request a pinned capture of the next `trigger_steps` steps.
        Thread-safe and cheap — only state flips here; the device tracer
        starts at the next begin_step(). Returns the capture id the
        trigger landed on (a new pending capture, or the pending/active
        one it coalesced into), or None when suppressed by cooldown."""
        trig = {"kind": str(kind), "step": None, "ts": time.time(),
                "row": row}
        with self._lock:
            self.triggers_total += 1
            trig["step"] = self._step
            tgt = self._active if self._active is not None else self._pending
            if tgt is not None:
                # coalesce: the storm's later alerts become evidence on
                # the one capture already in flight — and pin it (a
                # periodic capture that caught an anomaly is evidence)
                tgt["pinned"] = True
                tgt["triggers"].append(trig)
                tgt["steps_left"] = max(tgt["steps_left"],
                                        self.trigger_steps)
                self.triggers_coalesced += 1
                return tgt["id"]
            now = self._clock()
            if (self.cooldown_s > 0 and self._last_trigger_t is not None
                    and now - self._last_trigger_t < self.cooldown_s):
                self.triggers_suppressed += 1
                return None
            self._last_trigger_t = now
            self._pending = self._new_capture(
                "trigger", pinned=True, steps=self.trigger_steps,
                triggers=[trig])
            return self._pending["id"]

    def _new_capture(self, kind: str, *, pinned: bool, steps: int,
                     triggers: List[dict]) -> dict:
        self._seq += 1
        return {"id": f"c{self._seq:04d}", "kind": kind, "pinned": pinned,
                "steps_left": steps, "triggers": triggers,
                "step_first": None, "step_last": None,
                "t0": None, "_mono0": None,
                "trace_path": None, "wall_s": None, "error": None}

    # --------------------------------------------------------------- steps
    def begin_step(self):
        """Step boundary: start a due capture (pending trigger first,
        else a due periodic). Called from the step thread only — the one
        place the backend's start() runs."""
        cap = None
        with self._lock:
            self._step += 1
            if self._active is not None:
                return
            if self._pending is None and self.every > 0 \
                    and self._step >= self._next_periodic:
                self._pending = self._new_capture(
                    "periodic", pinned=False, steps=self.capture_steps,
                    triggers=[])
            cap, self._pending = self._pending, None
            if cap is None:
                return
            if self.every > 0:
                # any capture resets the periodic cadence — back-to-back
                # trigger + periodic captures of the same steps would be
                # duplicate evidence
                self._next_periodic = self._step + self.every
            cap["step_first"] = self._step
            cap["t0"] = time.time()
            cap["_mono0"] = time.monotonic()
            self._active = cap
        try:
            self.backend.start()
        except Exception as e:              # noqa: BLE001 — see docstring
            with self._lock:
                self.capture_errors += 1
                self._active = None
            _logger.warning("flightrec capture start failed: %s", e)

    def end_step(self):
        """Step boundary: one captured step elapsed; finalize the active
        capture when its step budget is spent."""
        with self._lock:
            cap = self._active
            if cap is None:
                return None
            cap["steps_left"] -= 1
            if cap["steps_left"] > 0:
                return None
            cap["step_last"] = self._step
            cap["wall_s"] = time.monotonic() - cap["_mono0"]
            self._active = None             # triggers now start fresh
        return self._finalize(cap)

    def _finalize(self, cap: dict) -> dict:
        dest = os.path.join(self.dir, f"{cap['id']}.trace.json.gz")
        try:
            cap["trace_path"] = self.backend.stop(dest)
        except Exception as e:              # noqa: BLE001 — see docstring
            cap["error"] = f"{type(e).__name__}: {e}"
            _logger.warning("flightrec capture %s failed: %s",
                            cap["id"], e)
        meta = self._meta(cap)
        with self._lock:
            self.captures.append(cap)
            self.captures_total += 1
            if cap["pinned"]:
                self.captures_pinned_total += 1
            if cap["error"] is not None:
                self.capture_errors += 1
            while len(self.captures) > self.ring:
                self._evict_one()
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps({"capture": meta, "ts": time.time()})
                        + "\n")
        if self.on_capture is not None:
            self.on_capture(meta)
        return cap

    def _evict_one(self):
        """Oldest periodic capture first; only a ring that is ALL pinned
        evicts its oldest pinned entry (the hard capacity bound)."""
        victim = next((c for c in self.captures if not c["pinned"]), None)
        if victim is None:
            victim = self.captures[0]
            self.evicted_pinned += 1
        else:
            self.evicted_periodic += 1
        self.captures.remove(victim)
        if victim.get("trace_path"):
            try:
                os.remove(victim["trace_path"])
            except OSError:
                pass

    # --------------------------------------------------------- trigger bus
    def attach(self, *, monitor=None, slo=None, metrics=None
               ) -> "FlightRecorder":
        """Wire the trigger bus into existing emitters, preserving any
        hook already installed (the chained previous hook still runs
        first). `monitor` additionally gets its `flightrec` slot set so
        its begin_step/end_step drive the capture state machine.
        `detach()` undoes everything in reverse order."""
        if monitor is not None:
            if getattr(monitor, "flightrec", None) is not None \
                    and monitor.flightrec is not self:
                raise ValueError("monitor already has a flight recorder "
                                 "attached")
            monitor.flightrec = self
            self._attached.append(("slot", monitor))
            self._chain(monitor, "on_report")
        if slo is not None:
            self._chain(slo, "on_alert")
        if metrics is not None:
            self._chain(metrics, "on_record")
        return self

    def _chain(self, obj, attr: str):
        prev = getattr(obj, attr, None)
        tap = self.tap

        def chained(row, _prev=prev, _tap=tap):
            if _prev is not None:
                _prev(row)
            _tap(row)
        setattr(obj, attr, chained)
        self._attached.append(("hook", obj, attr, prev))

    def detach(self):
        for entry in reversed(self._attached):
            if entry[0] == "slot":
                entry[1].flightrec = None
            else:
                _, obj, attr, prev = entry
                setattr(obj, attr, prev)
        self._attached = []
        return self

    def tap(self, row):
        """The trigger bus: sniff one structured row; anomaly rows
        request a capture, everything else is a dict-key probe."""
        if not isinstance(row, dict):
            return
        for key in TRIGGER_KEYS:
            if key in row:
                self.trigger(key, row)
                return
        num = row.get("numerics")
        if isinstance(num, dict) and num.get("events"):
            self.trigger("numerics", row)

    # ------------------------------------------------------------ reporting
    @staticmethod
    def _meta(cap: dict) -> dict:
        steps = None
        if cap["step_first"] is not None and cap["step_last"] is not None:
            steps = cap["step_last"] - cap["step_first"] + 1
        return {"id": cap["id"], "kind": cap["kind"],
                "pinned": cap["pinned"], "ts": cap["t0"],
                "step_first": cap["step_first"],
                "step_last": cap["step_last"], "steps": steps,
                "wall_s": (round(cap["wall_s"], 6)
                           if cap["wall_s"] is not None else None),
                "trace_path": cap["trace_path"], "error": cap["error"],
                "triggers": [{"kind": t["kind"], "step": t["step"],
                              "ts": t["ts"], "row": t["row"]}
                             for t in cap["triggers"]]}

    def summary(self) -> dict:
        with self._lock:
            pinned = sum(1 for c in self.captures if c["pinned"])
            return {"dir": self.dir, "ring": self.ring,
                    "retained": len(self.captures),
                    "retained_pinned": pinned,
                    "every": self.every,
                    "capture_steps": self.capture_steps,
                    "trigger_steps": self.trigger_steps,
                    "cooldown_s": self.cooldown_s,
                    "step": self._step,
                    "active": (self._active or {}).get("id"),
                    "pending": (self._pending or {}).get("id"),
                    "captures_total": self.captures_total,
                    "captures_pinned_total": self.captures_pinned_total,
                    "capture_errors": self.capture_errors,
                    "triggers_total": self.triggers_total,
                    "triggers_coalesced": self.triggers_coalesced,
                    "triggers_suppressed": self.triggers_suppressed,
                    "evicted_periodic": self.evicted_periodic,
                    "evicted_pinned": self.evicted_pinned}

    def metrics_text(self, prefix: str = "paddle_tpu_flightrec") -> str:
        from ..profiler._metrics import counter_lines, gauge_lines
        s = self.summary()
        lines: List[str] = []
        lines += gauge_lines(prefix, "ring_retained", s["retained"],
                             "captures currently in the ring")
        lines += gauge_lines(prefix, "ring_pinned", s["retained_pinned"],
                             "pinned captures currently in the ring")
        for name, val, help_ in (
                ("captures_total", s["captures_total"],
                 "captures finished"),
                ("captures_pinned_total", s["captures_pinned_total"],
                 "trigger-pinned captures"),
                ("capture_errors_total", s["capture_errors"],
                 "captures that failed"),
                ("triggers_total", s["triggers_total"],
                 "trigger-bus firings"),
                ("triggers_coalesced_total", s["triggers_coalesced"],
                 "triggers merged into an in-flight capture"),
                ("triggers_suppressed_total", s["triggers_suppressed"],
                 "triggers dropped by the cooldown window"),
                ("evictions_total",
                 s["evicted_periodic"] + s["evicted_pinned"],
                 "captures evicted from the ring")):
            lines += counter_lines(prefix, name, val, help_)
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------------ /profilez
    def _find(self, cid: str) -> dict:
        with self._lock:
            for c in self.captures:
                if c["id"] == cid:
                    return c
        raise ValueError(f"unknown capture id {cid!r}")

    def profilez(self, query: Optional[dict] = None):
        """TelemetryServer route handler. No `id`: the capture list
        (newest first) + summary. With `?id=`: `view=kernel|device|
        distributed` returns the view's structured rows AND its rendered
        table text (byte-identical to what `trace_analysis` prints from
        the same trace file); `fmt=raw` streams the trace.json.gz
        itself. ValueError on bad input -> HTTP 400."""
        q = query or {}
        cid = q.get("id")
        if not cid:
            with self._lock:
                caps = [self._meta(c) for c in reversed(self.captures)]
            return {"summary": self.summary(), "captures": caps}
        cap = self._find(cid)
        path = cap.get("trace_path")
        if not path or not os.path.exists(path):
            raise ValueError(f"capture {cid} has no trace file "
                             f"({cap.get('error') or 'evicted?'})")
        if q.get("fmt") == "raw":
            from .server import Raw
            with open(path, "rb") as f:
                body = f.read()
            ctype = "application/gzip" if path.endswith(".gz") \
                else "application/json"
            return Raw(body, content_type=ctype,
                       filename=os.path.basename(path))
        from ..profiler.trace_analysis import analyze
        steps = None
        if cap["step_first"] is not None and cap["step_last"] is not None:
            steps = cap["step_last"] - cap["step_first"] + 1
        an = analyze(path, steps=steps)
        view = q.get("view", "kernel")
        if view == "kernel":
            rows, table = an.op_totals(), an.kernel_view()
        elif view == "device":
            rows, table = an.lane_busy(), an.device_view()
        elif view in ("distributed", "collective", "collectives"):
            rows, table = an.collective_rows(), an.distributed_view()
        else:
            raise ValueError(f"unknown view {view!r}; one of "
                             f"kernel|device|distributed (or fmt=raw)")
        return {"capture": self._meta(cap), "view": view,
                "rows": rows, "table": table,
                "total_device_us": an.total_device_us(),
                "overlap": an.overlap()}
