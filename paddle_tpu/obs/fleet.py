"""FleetAggregator — N replica ops surfaces scraped into ONE (ISSUE 13).

r15 made every replica scrapeable; a router or autoscaler consuming N
separate pages re-implements aggregation badly (averaged percentiles, a
dead replica 500ing the dashboard). This module is the aggregation layer,
stdlib-only like the rest of obs/:

Merge semantics per metric TYPE (`merge_exposition`):

  counter     SUMMED across replicas per (family, label set) — fleet
              requests_total is the sum, exactly what a rate() wants.
  gauge       NEVER summed or averaged: each replica's sample is kept and
              labeled ``{replica="<name>"}`` (a fleet-mean queue depth of
              2 hides one replica at 0 and one at 4 — the router needs
              both; `/fleet/healthz` carries the sums that ARE meaningful,
              chosen by hand). Untyped families merge like gauges.
  histogram   merged BUCKET-WISE: the log-bucket histograms are mergeable
              by construction (same bucket layout on every replica since
              they run the same code), so per-`le` cumulative counts and
              `_sum`/`_count` just add. The fleet p99 then derives from
              the POOLED buckets — never from averaging per-replica
              percentiles, which is statistically meaningless. Replicas
              whose populated bounds cannot belong to one shared layout
              are rejected with a structured `FleetMergeError` naming the
              family and replicas (the check accepts any bound sets that
              fit one common geometric OR arithmetic grid — exposition
              pages elide empty buckets, so layout equality can only be
              checked up to the populated bounds).

Scrape-storm guard (ISSUE 14): member scrapes are cached per route for
``cache_ttl`` seconds (default 1 s; 0 disables), so N clients hammering
the fleet page cost the members ONE scrape per TTL window instead of N —
membership changes invalidate the cache, and cached responses never
touch the staleness bookkeeping below.

Staleness (the degrade rule): a replica whose scrape fails (connection
refused / timeout / bad payload) is marked ``stale`` and EXCLUDED from
the merge — the merged page keeps serving from the live replicas and the
fleet block reports the stale count; a scrape of the fleet endpoint never
500s because a member died. A stale replica rejoins automatically on its
next successful scrape. `/fleet/healthz` rolls the member healthz pages
into the autoscaler/router input: serving/draining/stale counts plus
summed queue depth, inflight and `overloaded_total`. `/fleet/tracez`
merges the members' tail-sampled trace rings on `trace_id` (unique
fleet-wide by construction: engine-run-uuid8 + request id), so two
aggregation layers — or one aggregator scraping twice — cannot
double-count a trace.
"""
from __future__ import annotations

import json
import math
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple
from urllib.error import HTTPError
from urllib.parse import urlencode
from urllib.request import urlopen

from ..profiler._metrics import (ExpositionError, format_value,
                                 parse_exposition)
from .registry import MetricsRegistry, lint_exposition

__all__ = ["FleetAggregator", "FleetMergeError", "merge_exposition",
           "bucket_percentile"]

_REL_TOL = 1e-6


class FleetMergeError(ExpositionError):
    """Replica pages cannot be merged (structured: .family, .replicas,
    .detail survive for programmatic handling)."""

    def __init__(self, family: str, detail: str, replicas=()):
        self.family = family
        self.detail = detail
        self.replicas = sorted(replicas)
        super().__init__(f"cannot merge family {family!r} across "
                         f"replicas {self.replicas}: {detail}")

    def to_dict(self) -> dict:
        return {"error": "fleet_merge", "family": self.family,
                "replicas": self.replicas, "detail": self.detail}


# ------------------------------------------------------------- merge math

def _common_step(gaps: List[float]) -> float:
    """Approximate real GCD of the gaps (symmetric-remainder Euclid with
    a relative tolerance): the candidate grid step the bounds sit on.
    Incommensurable gaps (bounds from two different layouts) drive this
    toward zero instead of a sensible step."""
    tol = max(gaps) * _REL_TOL
    g = gaps[0]
    for d in gaps[1:]:
        a, b = max(g, d), min(g, d)
        while b > tol:
            r = math.fmod(a, b)
            r = min(r, abs(b - r))      # nearest-integer quotient:
            #                             2.0 % 0.5 must read as 0, not
            #                             ~0.4999 fp noise
            a, b = b, r
        g = a
    return g


def _grid_consistent(bounds: List[float]) -> bool:
    """Can these populated bucket bounds all belong to ONE layout?

    Exposition pages elide empty buckets, so the full layout is not
    observable; the necessary condition checked here is that the union
    fits a single geometric grid (log-spaced latency histograms: gaps in
    log10 space share a common step) or a single arithmetic grid (the
    half-integer spec_accept_len bounds: linear gaps share one). The
    common step comes from a real-GCD of the gaps; bounds from disjoint
    layouts (a shifted lo, a log grid mixed into a linear one) drive the
    GCD toward zero, detected as a step implausibly finer than the
    smallest observed gap. Nested refinements of one grid pass — merging
    them is still a valid cumulative histogram, each replica contributing
    at its own bucket resolution."""
    if len(bounds) <= 2:
        return True

    def fits(gaps: List[float]) -> bool:
        if min(gaps) <= 0:
            return False
        g = _common_step(gaps)
        # a real layout's populated bounds sit a handful of grid steps
        # apart; a pseudo-step 64x finer than the closest observed pair
        # is the incommensurable case converging toward zero
        return g >= min(gaps) / 64.0

    lin = [b - a for a, b in zip(bounds, bounds[1:])]
    if fits(lin):
        return True
    if all(b > 0 for b in bounds):
        logs = [math.log10(b) for b in bounds]
        if fits([b - a for a, b in zip(logs, logs[1:])]):
            return True
    return False


def _hist_parts(name: str, fam: dict) -> Tuple[List[Tuple[float, float]],
                                               float, float]:
    """(finite (le, cumulative) buckets ascending, count, sum) of one
    replica's histogram family."""
    buckets: List[Tuple[float, float]] = []
    count = total = 0.0
    for base, labels, value in fam["samples"]:
        if base == f"{name}_bucket":
            le = labels[1:-1].split("=", 1)[1].strip('"')
            if le != "+Inf":
                buckets.append((float(le), float(value)))
        elif base == f"{name}_count":
            count = float(value)
        elif base == f"{name}_sum":
            total = float(value)
    buckets.sort()
    return buckets, count, total


def _merge_histogram(name: str, per_replica: Dict[str, dict]) -> List[str]:
    parts = {rep: _hist_parts(name, fam)
             for rep, fam in per_replica.items()}
    bounds = sorted({b for bks, _, _ in parts.values() for b, _ in bks})
    if not _grid_consistent(bounds):
        raise FleetMergeError(
            name, f"populated bucket bounds {bounds} do not fit one "
                  f"layout — replicas must run the same histogram config "
                  f"(lo/hi/per_decade) for bucket-wise pooling to be "
                  f"meaningful", per_replica)
    count = sum(c for _, c, _ in parts.values())
    total = sum(s for _, _, s in parts.values())
    lines: List[str] = []
    prev_cum = 0.0
    for u in bounds:
        cum = 0.0
        for bks, _, _ in parts.values():
            # cumulative at u = the replica's cumulative at its largest
            # populated bound <= u (elided buckets held zero, so the
            # cumulative count is flat between populated bounds)
            at = 0.0
            for b, c in bks:
                if b <= u:
                    at = c
                else:
                    break
            cum += at
        if cum > prev_cum:      # elide empty merged buckets like the
            #                     renderer does; cumulativity unaffected
            lines.append(f'{name}_bucket{{le="{format_value(u)}"}} '
                         f'{format_value(cum)}')
        prev_cum = cum
    lines.append(f'{name}_bucket{{le="+Inf"}} {format_value(count)}')
    lines.append(f"{name}_sum {format_value(total)}")
    lines.append(f"{name}_count {format_value(count)}")
    return lines


def _with_replica(labels: str, replica: str) -> str:
    inner = labels[1:-1].strip() if labels else ""
    parts = [f'replica="{replica}"'] + ([inner] if inner else [])
    return "{" + ",".join(parts) + "}"


def merge_exposition(pages: Dict[str, str], *,
                     validate: bool = True) -> str:
    """Merge per-replica exposition pages into one (module docstring for
    the per-type semantics). `pages` maps replica name -> page text; an
    empty/blank page contributes nothing (a young replica is not an
    error). The result is family-contiguous and lint-clean by
    construction; `validate=True` lints each input page first so a broken
    REPLICA page is named rather than corrupting the merge."""
    parsed: Dict[str, dict] = {}
    for rep, text in pages.items():
        if text is None or not text.strip():
            continue
        try:
            parsed[rep] = lint_exposition(text) if validate \
                else parse_exposition(text)
        except ExpositionError as e:
            raise FleetMergeError("<page>", f"replica page does not "
                                  f"lint: {e}", [rep]) from e
    order: List[str] = []
    owners: Dict[str, Dict[str, dict]] = {}
    for rep, fams in parsed.items():
        for name, fam in fams.items():
            if name not in owners:
                owners[name] = {}
                order.append(name)
            owners[name][rep] = fam
    out: List[str] = []
    for name in order:
        per = owners[name]
        kinds = {fam["type"] for fam in per.values()}
        if len(kinds) > 1:
            raise FleetMergeError(name, f"replicas disagree on TYPE "
                                  f"({sorted(kinds)})", per)
        kind = kinds.pop()
        first = next(iter(per.values()))
        out.append(f"# HELP {name} {first['help']}")
        out.append(f"# TYPE {name} {kind}")
        if kind == "counter":
            sums: Dict[str, float] = {}
            key_order: List[str] = []
            for rep, fam in per.items():
                for base, labels, value in fam["samples"]:
                    key = f"{base}{labels}"
                    if key not in sums:
                        sums[key] = 0.0
                        key_order.append(key)
                    sums[key] += float(value)
            out += [f"{key} {format_value(sums[key])}"
                    for key in key_order]
        elif kind == "histogram":
            out += _merge_histogram(name, per)
        else:                    # gauge / untyped: label per replica
            for rep, fam in per.items():
                out += [f"{base}{_with_replica(labels, rep)} "
                        f"{value}"
                        for base, labels, value in fam["samples"]]
    return "\n".join(out) + "\n" if out else ""


def bucket_percentile(buckets: List[Tuple[float, float]], count: float,
                      q: float) -> Optional[float]:
    """Percentile from parsed cumulative (le, cum) exposition buckets —
    the read-side twin of LogHistogram.percentile for a scraped page
    (without the recorder's min/max clamp, so edges resolve to bucket
    bounds; relative error stays bounded by the bucket ratio). `buckets`
    ascending with the +Inf bucket as float('inf')."""
    if not count:
        return None
    target = q * count
    prev_bound = None
    prev_cum = 0.0
    for bound, cum in buckets:
        if cum >= target and cum > prev_cum:
            if math.isinf(bound):
                return prev_bound
            lo = prev_bound if prev_bound is not None else 0.0
            frac = (target - prev_cum) / (cum - prev_cum)
            return lo + frac * (bound - lo)
        if cum > prev_cum:
            prev_bound = bound
            prev_cum = cum
    return prev_bound


# ------------------------------------------------------------- aggregator

class _Replica:
    def __init__(self, name: str, base_url: str):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.stale = False
        self.consecutive_failures = 0
        self.last_ok: Optional[float] = None
        self.last_error: Optional[str] = None

    def mark_ok(self):
        self.stale = False
        self.consecutive_failures = 0
        self.last_ok = time.time()
        self.last_error = None

    def mark_failed(self, err: str):
        self.stale = True
        self.consecutive_failures += 1
        self.last_error = err

    def state(self) -> dict:
        return {"url": self.base_url, "stale": self.stale,
                "consecutive_failures": self.consecutive_failures,
                "last_ok_ts": self.last_ok,
                "last_error": self.last_error}


class FleetAggregator:
    """Scrape N TelemetryServer replicas, serve ONE merged surface.

        fleet = FleetAggregator({"r0": srv0.url(), "r1": srv1.url()})
        page = fleet.merged_metrics()      # lint-clean, pooled
        fleet.fleet_healthz()              # the autoscaler roll-up
        agg_srv = fleet.serve()            # /metrics /healthz
                                           # /fleet/healthz /fleet/tracez

    `replicas`: {name: base_url} (or an iterable of (name, url) /
    TelemetryServer instances — a server contributes its url() under the
    name replicaN). Scrapes run concurrently (one slow member must not
    serialize the page) with `timeout` seconds per request; failures mark
    the member stale per the module-docstring degrade rule.
    """

    def __init__(self, replicas=None, *, timeout: float = 2.0,
                 prefix: str = "paddle_tpu_fleet",
                 cache_ttl: float = 1.0):
        self.timeout = float(timeout)
        self.prefix = prefix
        self.scrapes_total = 0
        self.scrape_errors_total = 0
        self.scrape_cache_hits_total = 0
        # scrape-storm guard (ISSUE 14 satellite): member scrapes were
        # pull-through, so N dashboard clients hitting the fleet page
        # multiplied into N scrapes of every member's /metrics. Results
        # are now cached per route for `cache_ttl` seconds (0 disables)
        # — staleness bookkeeping is untouched because cached responses
        # never touch mark_ok/mark_failed, and membership changes
        # invalidate the cache so an added/removed replica shows up in
        # the very next scrape.
        self.cache_ttl = float(cache_ttl)
        self._cache: Dict[str, Tuple[float, Dict[str, object]]] = {}
        self._cache_gen = 0     # membership generation: a scrape that
        # started before an add/remove must not store its pre-change
        # snapshot over the invalidation
        self._replicas: Dict[str, _Replica] = {}
        self._lock = threading.Lock()
        # one long-lived scrape pool: the fleet /metrics route is pull-
        # through, so a per-call executor would churn threads on every
        # scrape of every route (close() tears it down; workers are
        # urlopen calls with timeouts, so shutdown is bounded)
        self._pool = ThreadPoolExecutor(
            max_workers=16, thread_name_prefix="paddle-tpu-fleet-scrape")
        # active-probing scope (ISSUE 19): config-drift detection is
        # transition-based — ONE structured {"config_drift"} finding
        # when the fleet's /statusz fingerprints stop agreeing, handed
        # to `on_finding` (e.g. a ServingMetrics._emit bound method) and
        # retained in `findings` for the /fleet/probez payload.
        self.on_finding: Optional[Callable[[dict], None]] = None
        self.findings: List[dict] = []
        self._config_drift = False
        for name, url in self._coerce(replicas):
            self.add_replica(name, url)

    def close(self):
        """Release the scrape thread pool. Safe to call more than once;
        a served aggregator should close AFTER its TelemetryServer."""
        self._pool.shutdown(wait=False)

    @staticmethod
    def _coerce(replicas) -> List[tuple]:
        """(name, url-or-TelemetryServer) pairs; add_replica finishes the
        coercion so servers work in every container shape."""
        if replicas is None:
            return []
        if isinstance(replicas, dict):
            return [(str(k), v) for k, v in replicas.items()]
        out = []
        for i, item in enumerate(replicas):
            if isinstance(item, tuple):
                out.append((str(item[0]), item[1]))
            else:
                out.append((f"replica{i}", item))
        return out

    def add_replica(self, name: str, url_or_server) -> "FleetAggregator":
        url = url_or_server.url("/") if hasattr(url_or_server, "url") \
            else str(url_or_server)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica {name!r} already registered")
            self._replicas[name] = _Replica(name, url)
            self._cache.clear()     # membership change: next scrape is
            self._cache_gen += 1    # fresh so the member shows up now
            return self

    def remove_replica(self, name: str) -> bool:
        with self._lock:
            self._cache.clear()
            self._cache_gen += 1
            return self._replicas.pop(name, None) is not None

    @property
    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._replicas)

    def replica_states(self) -> Dict[str, dict]:
        with self._lock:
            return {n: r.state() for n, r in self._replicas.items()}

    # ------------------------------------------------------------ scraping
    def _get(self, url: str, ok_codes: Tuple[int, ...] = ()) -> bytes:
        """GET with the per-route error policy: some HTTPError bodies ARE
        the payload (a draining replica's /healthz is a 503 WITH the JSON
        the roll-up needs; a bufferless /tracez is a 404 saying so) —
        those codes pass through; anything else raises and the member
        degrades to stale (a replica whose /metrics 500s is dead for
        metrics purposes — its broken producer must not take the FLEET
        page down)."""
        try:
            with urlopen(url, timeout=self.timeout) as resp:
                return resp.read()
        except HTTPError as e:
            body = e.read()
            if e.code in ok_codes and body:
                return body
            raise

    def _scrape_route(self, route: str,
                      decode: Callable[[bytes], object],
                      ok_codes: Tuple[int, ...] = ()) -> Dict[str, object]:
        """GET one route from every replica concurrently; successes update
        liveness, failures mark stale. Returns {name: decoded} for the
        replicas that answered. Within `cache_ttl` seconds of the last
        scrape of the SAME route the cached result is returned without
        touching any member (the scrape-storm guard — see __init__)."""
        now = time.monotonic()
        with self._lock:
            if self.cache_ttl > 0:
                hit = self._cache.get(route)
                if hit is not None and now - hit[0] < self.cache_ttl:
                    self.scrape_cache_hits_total += 1
                    return dict(hit[1])
            gen = self._cache_gen
            members = list(self._replicas.values())
            self.scrapes_total += 1
        if not members:
            return {}
        results: Dict[str, object] = {}

        def one(rep: _Replica):
            return decode(self._get(rep.base_url + route, ok_codes))

        futs = {self._pool.submit(one, rep): rep for rep in members}
        for fut, rep in futs.items():
            try:
                payload = fut.result()
            except Exception as e:          # noqa: BLE001 — the degrade
                # rule: a dead member goes stale; the fleet surface
                # keeps serving from the rest
                with self._lock:            # counters are exposed on the
                    # fleet block and handlers run on many server
                    # threads: unsynchronized += drops increments
                    self.scrape_errors_total += 1
                rep.mark_failed(f"{type(e).__name__}: {e}")
                continue
            rep.mark_ok()
            results[rep.name] = payload
        if self.cache_ttl > 0:
            with self._lock:
                if self._cache_gen == gen:  # membership unchanged since
                    # the member list was snapped — safe to cache; a
                    # concurrent add/remove wins otherwise
                    self._cache[route] = (now, dict(results))
        return results

    # ------------------------------------------------------------- surface
    def merged_metrics(self) -> str:
        """One fresh scrape of every member's /metrics, merged + the
        aggregator's own fleet block, linted before it leaves. Stale
        members are degraded around; a FleetMergeError (mismatched
        layouts, TYPE disagreement) is a REAL error and propagates —
        silently dropping a replica's data would be worse than failing
        the scrape visibly."""
        pages = self._scrape_route(
            "/metrics", lambda b: b.decode("utf-8", "replace"))
        merged = merge_exposition(pages)
        page = self._fleet_block() + merged
        lint_exposition(page)
        return page

    def _fleet_block(self) -> str:
        states = self.replica_states()
        stale = sum(1 for s in states.values() if s["stale"])
        p = self.prefix
        lines = [
            f"# HELP {p}_replicas registered replicas by liveness",
            f"# TYPE {p}_replicas gauge",
            f'{p}_replicas{{state="live"}} {len(states) - stale}',
            f'{p}_replicas{{state="stale"}} {stale}',
            f"# HELP {p}_up replica answered its last scrape",
            f"# TYPE {p}_up gauge"]
        lines += [f'{p}_up{{replica="{n}"}} '
                  f'{0 if s["stale"] else 1}'
                  for n, s in sorted(states.items())]
        lines += [
            f"# HELP {p}_scrape_errors_total failed member scrapes",
            f"# TYPE {p}_scrape_errors_total counter",
            f"{p}_scrape_errors_total {self.scrape_errors_total}",
            f"# HELP {p}_scrape_cache_hits_total member scrapes served "
            f"from the TTL cache (scrape-storm guard)",
            f"# TYPE {p}_scrape_cache_hits_total counter",
            f"{p}_scrape_cache_hits_total {self.scrape_cache_hits_total}"]
        return "\n".join(lines) + "\n"

    def fleet_healthz(self, _query: Optional[dict] = None) -> dict:
        """The roll-up a router/autoscaler consumes: member healthz pages
        summed where summing means something (queue depth, inflight,
        overloaded/rejected totals) and counted where it does not
        (serving/draining/stale states). `status` is "ok" while at least
        one member serves; "unserviceable" (-> HTTP 503 through a
        TelemetryServer health route) when none does — the fleet-level LB
        ejection signal."""
        payloads = self._scrape_route("/healthz", json.loads,
                                      ok_codes=(503,))
        states = self.replica_states()
        serving = draining = 0
        sums = {"queue_depth": 0, "queue_capacity": 0, "inflight": 0,
                "overloaded_total": 0, "rejected_total": 0}
        per: Dict[str, dict] = {}
        for name, state in sorted(states.items()):
            h = payloads.get(name)
            if h is None:
                per[name] = {"state": "stale", **state}
                continue
            # healthz payloads are parsed JSON — host dicts, never
            # tensors  # lint: allow(tracer-bool)
            is_draining = bool(h.get("draining")) \
                or h.get("status") == "draining"
            draining += 1 if is_draining else 0
            serving += 0 if is_draining else 1
            for key in sums:
                v = h.get(key)
                if isinstance(v, (int, float)):
                    sums[key] += v
            per[name] = {"state": "draining" if is_draining
                         else "serving", **{k: h.get(k) for k in
                                            ("queue_depth", "inflight",
                                             "overloaded_total")}}
        return {"status": "ok" if serving else "unserviceable",
                "replicas": len(states),
                "serving": serving, "draining": draining,
                "stale": len(states) - serving - draining,
                **sums,
                "per_replica": per}

    def fleet_tracez(self, query: Optional[dict] = None) -> dict:
        """Member /tracez rings merged on trace_id. Query params (the
        /fleet/tracez route forwards them): limit (per the MERGED view,
        default 64), status, order=recent|slowest. Each retained trace
        carries its `replica`; duplicates (same trace_id seen via two
        scrape paths) keep the first copy."""
        query = query or {}
        limit = int(query.get("limit", 64))
        status = query.get("status")
        order = query.get("order", "recent")
        if order not in ("recent", "slowest"):
            raise ValueError(f"order must be 'recent' or 'slowest', "
                             f"got {order!r}")
        member_q = f"/tracez?limit={max(limit, 1)}" \
            + (f"&status={status}" if status else "") \
            + (f"&order={order}" if order else "")
        payloads = self._scrape_route(member_q, json.loads,
                                      ok_codes=(404,))
        seen = set()
        merged: List[dict] = []
        summaries: Dict[str, dict] = {}
        # round-robin over members preserves each ring's newest-first
        # order in the "recent" view without a shared clock
        iters = {name: iter(p.get("traces", []))
                 for name, p in sorted(payloads.items())}
        for name, p in payloads.items():
            summaries[name] = p.get("summary", {})
        while iters:
            for name in list(iters):
                try:
                    rec = next(iters[name])
                except StopIteration:
                    del iters[name]
                    continue
                tid = rec.get("trace_id") or f"{name}/{rec.get('id')}"
                if tid in seen:
                    continue
                seen.add(tid)
                merged.append(dict(rec, replica=name))
        if order == "slowest":
            merged.sort(key=lambda r: -(r.get("e2e_s") or 0.0))
        merged = merged[:max(limit, 0)]
        retained = sum(s.get("retained", 0) for s in summaries.values())
        return {"summary": {"replicas": len(self.replica_states()),
                            "answered": len(payloads),
                            "retained": retained,
                            "merged": len(merged),
                            "per_replica": summaries},
                "traces": merged}

    def fleet_profilez(self, query: Optional[dict] = None) -> dict:
        """Member /profilez surfaces merged (ISSUE 17), tracez-style.

        List mode (no `replica` param): every member's capture ring in
        one list, each capture labeled `replica`, newest first; members
        without a flight recorder (404) just contribute nothing. Detail
        mode (`?replica=NAME&id=...&view=...` or `&fmt=raw`): the query
        is proxied verbatim to that member — the view tables and the
        raw trace download render on the replica that owns the trace
        file, so captures never move over the fleet scrape path."""
        query = dict(query or {})
        rep_name = query.pop("replica", None)
        if rep_name is not None:
            with self._lock:
                rep = self._replicas.get(rep_name)
            if rep is None:
                raise ValueError(f"unknown replica {rep_name!r}")
            qs = urlencode(query)
            url = rep.base_url + "/profilez" + (f"?{qs}" if qs else "")
            body = self._get(url, ok_codes=(400, 404))
            try:
                payload = json.loads(body)
            except (UnicodeDecodeError, json.JSONDecodeError):
                # non-JSON body: the raw trace download — stream it
                from .server import Raw
                return Raw(body, content_type="application/gzip",
                           filename=f"{rep_name}-"
                                    f"{query.get('id', 'trace')}"
                                    ".trace.json.gz")
            if isinstance(payload, dict) and "error" in payload \
                    and "captures" not in payload:
                raise ValueError(f"{rep_name}: {payload['error']}")
            return dict(payload, replica=rep_name)
        payloads = self._scrape_route("/profilez", json.loads,
                                      ok_codes=(404,))
        merged: List[dict] = []
        summaries: Dict[str, dict] = {}
        for name, p in sorted(payloads.items()):
            if not isinstance(p, dict) or "captures" not in p:
                continue                # 404 body: no recorder attached
            summaries[name] = p.get("summary", {})
            merged.extend(dict(c, replica=name)
                          for c in p.get("captures", []))
        merged.sort(key=lambda c: -(c.get("ts") or 0.0))
        return {"summary": {"replicas": len(self.replica_states()),
                            "answered": len(payloads),
                            "with_recorder": len(summaries),
                            "captures": len(merged),
                            "per_replica": summaries},
                "captures": merged}

    def fleet_memz(self, query: Optional[dict] = None) -> dict:
        """Member /memz censuses merged with per-replica labels (ISSUE
        18). Every owner row carries its `replica`; the summary sums the
        conservation columns fleet-wide (attributed / allocated /
        unattributed / headroom — a None anywhere degrades that sum to
        None rather than inventing bytes) and keeps each member's full
        census under per_replica. Members without a ledger (404) and
        dead members contribute nothing — degraded, never fatal."""
        query = dict(query or {})
        deltas = query.get("deltas")
        member_q = "/memz" + (f"?deltas={int(deltas)}"
                              if deltas is not None else "")
        payloads = self._scrape_route(member_q, json.loads,
                                      ok_codes=(404,))
        owners: List[dict] = []
        per: Dict[str, dict] = {}
        sums = {"attributed_bytes": 0, "allocated_bytes": 0,
                "unattributed_bytes": 0, "headroom_bytes": 0}
        degraded = set()
        pressure = []
        for name, p in sorted(payloads.items()):
            if not isinstance(p, dict) or "owners" not in p:
                continue                # 404 body: no ledger attached
            per[name] = p
            owners.extend(dict(o, replica=name)
                          for o in p.get("owners", []))
            for k in sums:
                v = p.get(k)
                if v is None:
                    degraded.add(k)
                else:
                    sums[k] += int(v)
            if p.get("headroom_low"):
                pressure.append(name)
        for k in degraded:
            sums[k] = None
        owners.sort(key=lambda o: -(o.get("bytes") or 0))
        return {"summary": {"replicas": len(self.replica_states()),
                            "answered": len(payloads),
                            "with_ledger": len(per),
                            "headroom_low": sorted(pressure),
                            **sums},
                "owners": owners,
                "per_replica": per}

    def fleet_probez(self, _query: Optional[dict] = None) -> dict:
        """Member /probez states merged fleet-wide (ISSUE 19) plus
        config-drift detection over the /statusz fingerprints.

        The summary lists which replicas are correctness-`failing` (what
        the FleetRouter ejects on) and every member's config/build
        fingerprint; goldens are keyed by that fingerprint, so when the
        shas disagree the page both flags `config_drift` AND explains
        any probe misses on the odd replica out. Drift emission is
        transition-based: entering disagreement appends ONE structured
        `{"config_drift"}` finding (and calls `on_finding`); members
        without a prober (404 on /probez) still contribute their
        fingerprint — drift detection does not require probing."""
        payloads = self._scrape_route("/probez", json.loads,
                                      ok_codes=(404,))
        status = self._scrape_route("/statusz", json.loads,
                                    ok_codes=(404,))
        per: Dict[str, dict] = {}
        failing: List[str] = []
        for name, p in sorted(payloads.items()):
            if not isinstance(p, dict) or "variants" not in p:
                continue                # 404 body: no prober attached
            per[name] = p
            if p.get("state") == "failing":
                failing.append(name)
        fingerprints: Dict[str, str] = {}
        for name, s in sorted(status.items()):
            fp = s.get("fingerprint") if isinstance(s, dict) else None
            if isinstance(fp, dict) and fp.get("sha"):
                fingerprints[name] = fp["sha"]
        drift = len(set(fingerprints.values())) > 1
        if drift and not self._config_drift:
            finding = {"config_drift": {"fingerprints": dict(fingerprints)},
                       "ts": time.time()}
            self.findings.append(finding)
            del self.findings[:-64]
            if self.on_finding is not None:
                try:
                    self.on_finding(finding)
                except Exception:
                    pass        # a finding sink must never break scrapes
        self._config_drift = drift
        return {"summary": {"replicas": len(self.replica_states()),
                            "answered": len(payloads),
                            "with_prober": len(per),
                            "failing": sorted(failing),
                            "fingerprints": fingerprints,
                            "config_drift": drift},
                "per_replica": per,
                "findings": self.findings[-4:]}

    def fleet_statusz(self, _query: Optional[dict] = None) -> dict:
        return {"replicas": self.replica_states(),
                "scrapes_total": self.scrapes_total,
                "scrape_errors_total": self.scrape_errors_total,
                "scrape_cache_hits_total": self.scrape_cache_hits_total,
                "cache_ttl_s": self.cache_ttl,
                "timeout_s": self.timeout}

    # -------------------------------------------------------------- serve
    def serve(self, *, host: str = "127.0.0.1", port: int = 0):
        """A started TelemetryServer over this aggregator: /metrics = the
        merged page (scraped fresh per request), /healthz = the roll-up
        (503 when zero members serve), /statusz = member liveness, plus
        the explicit /fleet/healthz and /fleet/tracez routes the ISSUE
        names (handy when the aggregator page is mounted next to a
        replica's behind one proxy)."""
        from .server import TelemetryServer
        reg = MetricsRegistry()
        # the merged page is already one fully-rendered exposition; keep
        # the registry as the composition point (a co-hosted SLO/goodput
        # producer can still be registered beside it)
        reg.register("fleet", self.merged_metrics)
        srv = TelemetryServer(
            reg, host=host, port=port,
            health=self.fleet_healthz, status=self.fleet_statusz,
            routes={"/fleet/healthz": self.fleet_healthz,
                    "/fleet/tracez": self.fleet_tracez,
                    "/fleet/profilez": self.fleet_profilez,
                    "/fleet/memz": self.fleet_memz,
                    "/fleet/probez": self.fleet_probez,
                    "/fleet/statusz": self.fleet_statusz})
        srv.fleet = self
        return srv.start()
