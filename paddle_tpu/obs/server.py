"""TelemetryServer — the replica's ops surface, stdlib-only.

One threaded HTTP server (http.server.ThreadingHTTPServer on a daemon
thread; no new dependencies) exposes the four endpoints the fleet layer
scrapes — each aimed at a specific consumer:

  /metrics   Prometheus exposition from a MetricsRegistry (dashboard /
             metrics pipeline). Collision-checked and lint-clean per
             scrape; a broken producer 500s loudly.
  /healthz   JSON liveness + load: drain state, queue depth, inflight,
             overloaded_total — exactly the autoscaler/router inputs.
             HTTP 200 while serving, 503 while draining (the code a load
             balancer keys ejection on; the body says why).
  /statusz   JSON config/occupancy snapshot (humans + fleet inventory).
  /tracez    tail-sampled request traces from a TraceBuffer
             (?order=slowest&limit=N&status=timeout) — "why was p99
             slow" without logging every request.

The handlers only READ host-side telemetry state (counter/gauge dicts,
the trace ring, config scalars) — they never touch device state or the
engine's serving loop, so a scrape cannot trigger a compile, a sync or a
lock-order inversion with the serving thread. That is the whole design:
the ops surface rides the accounting the engine already keeps.

Two fleet-era extensions (ISSUE 13):

  `routes={...}`   extra JSON GET routes — a handler is a callable taking
                   the (single-valued) query-param dict and returning a
                   JSON-able payload; `FleetAggregator.serve()` mounts
                   /fleet/healthz and /fleet/tracez this way.
  `add_poller()`   a server-OWNED timer thread calling `fn()` every
                   `interval` seconds between start() and close() — the
                   cadence owner the SLOMonitor NOTE asked for: burn-rate
                   evaluation (and its push alerts) run without any
                   external driver, and the thread dies with the server.
"""
from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional
from urllib.parse import parse_qs, urlsplit

_logger = logging.getLogger("paddle_tpu.obs.server")

from .registry import MetricsRegistry
from .tracez import TraceBuffer

__all__ = ["TelemetryServer", "Raw"]

_CONTENT_PROM = "text/plain; version=0.0.4; charset=utf-8"
_CONTENT_JSON = "application/json; charset=utf-8"


class Raw:
    """A non-JSON payload an extra-route handler may return: raw bytes +
    content type (+ optional download filename). Lets a route stream a
    binary artifact — /profilez's trace.json.gz download — through the
    same dispatch that serves JSON."""

    __slots__ = ("body", "content_type", "filename")

    def __init__(self, body: bytes,
                 content_type: str = "application/octet-stream",
                 filename: Optional[str] = None):
        self.body = bytes(body)
        self.content_type = content_type
        self.filename = filename


def _json_default(o):
    try:
        import numpy as np
        if isinstance(o, np.generic):
            # isinstance-guarded numpy scalar: host data by construction
            return o.item()  # lint: allow(tracer-item)
        if isinstance(o, np.ndarray):
            return o.tolist()
    except Exception:
        pass
    return repr(o)


class _Handler(BaseHTTPRequestHandler):
    server_version = "paddle-tpu-telemetry/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):               # quiet: scrapes are chatty
        pass

    def _send(self, code: int, body: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload):
        self._send(code, (json.dumps(payload, default=_json_default)
                          + "\n").encode(), _CONTENT_JSON)

    def do_GET(self):                           # noqa: N802 (stdlib name)
        url = urlsplit(self.path)
        route = "/" + url.path.strip("/")
        srv: "TelemetryServer" = self.server.telemetry
        try:
            extra = srv.routes.get(route)
            if extra is not None:
                q = parse_qs(url.query)
                try:
                    payload = extra({k: v[0] for k, v in q.items() if v})
                except ValueError as e:
                    # handler contract: ValueError = bad CLIENT input
                    # (?limit=abc) — a 400, not a 500 a monitor would
                    # page on as an aggregator failure
                    self._send_json(400, {"error": str(e)})
                    return
                if isinstance(payload, Raw):
                    self.send_response(200)
                    self.send_header("Content-Type", payload.content_type)
                    if payload.filename:
                        self.send_header(
                            "Content-Disposition",
                            f'attachment; filename="{payload.filename}"')
                    self.send_header("Content-Length",
                                     str(len(payload.body)))
                    self.end_headers()
                    self.wfile.write(payload.body)
                    return
                self._send_json(200, payload if payload is not None
                                else {})
            elif route == "/metrics":
                body = srv.registry.render().encode()
                self._send(200, body, _CONTENT_PROM)
            elif route == "/healthz":
                payload = srv._call(srv.health) or {"status": "ok"}
                code = 200 if payload.get("status") == "ok" else 503
                self._send_json(code, payload)
            elif route == "/statusz":
                self._send_json(200, srv._call(srv.status) or {})
            elif route == "/tracez":
                if srv.tracez is None:
                    self._send_json(404, {"error": "no trace buffer "
                                                   "attached"})
                    return
                q = parse_qs(url.query)

                def one(key, default=None):
                    v = q.get(key)
                    return v[0] if v else default
                traces = srv.tracez.snapshot(
                    limit=int(one("limit", 64)),
                    status=one("status"),
                    order=one("order", "recent"))
                if one("fmt") == "chrome":
                    # Perfetto/Chrome trace-event export (ISSUE 17): the
                    # span trees as a timeline ui.perfetto.dev loads
                    from .tracez import chrome_trace
                    self._send_json(200, chrome_trace(traces))
                    return
                self._send_json(200, {"summary": srv.tracez.summary(),
                                      "traces": traces})
            else:
                self._send_json(404, {"error": f"unknown route {route}",
                                      "routes": ["/metrics", "/healthz",
                                                 "/statusz", "/tracez"]
                                      + sorted(srv.routes)})
        except BrokenPipeError:
            pass                                # scraper hung up; its call
        except Exception as e:                  # noqa: BLE001 — a broken
            # producer must fail THE SCRAPE (visibly), not the server
            try:
                self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass


class TelemetryServer:
    """See module docstring.

        srv = TelemetryServer(registry, health=engine.health,
                              status=engine.statusz,
                              tracez=buffer).start()
        ... curl http://127.0.0.1:{srv.port}/metrics ...
        srv.close()

    `port=0` binds an ephemeral port (read `.port` after construction —
    the socket binds in __init__, requests are served once `start()`
    spins the thread). `health`/`status` are zero-arg callables returning
    JSON-able dicts; `tracez` a TraceBuffer (or None to 404 the route).
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 health: Optional[Callable[[], dict]] = None,
                 status: Optional[Callable[[], dict]] = None,
                 tracez: Optional[TraceBuffer] = None,
                 routes: Optional[Dict[str, Callable]] = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.health = health
        self.status = status
        self.tracez = tracez
        # extra JSON routes: "/fleet/healthz" -> fn(query_dict) -> payload
        self.routes = {("/" + r.strip("/")): fn
                       for r, fn in (routes or {}).items()}
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.telemetry = self
        self._thread: Optional[threading.Thread] = None
        self._pollers: list = []

    @staticmethod
    def _call(fn):
        return fn() if fn is not None else None

    # ------------------------------------------------------------- routes
    def add_route(self, route: str, fn: Callable) -> "TelemetryServer":
        """Mount an extra GET route on a live server (same handler
        contract as the `routes=` ctor arg). Replaces any previous
        handler at that path."""
        self.routes["/" + route.strip("/")] = fn
        return self

    def remove_route(self, route: str) -> "TelemetryServer":
        self.routes.pop("/" + route.strip("/"), None)
        return self

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def url(self, route: str = "/") -> str:
        return f"http://{self.host}:{self.port}/{route.lstrip('/')}"

    # ------------------------------------------------------------ pollers
    def add_poller(self, fn: Callable[[], object], interval: float,
                   name: str = "poller") -> "TelemetryServer":
        """Own a timer thread calling `fn()` every `interval` seconds
        for the server's lifetime (first call one interval after start —
        a burn-rate window needs traffic before it means anything). A
        raising poll is logged and counted on the poller record, never
        fatal: the alerting loop must not die on one transient. Threads
        start with start() and stop with close()."""
        if interval is None or interval <= 0:
            raise ValueError(f"poller interval must be > 0, "
                             f"got {interval}")
        rec = {"fn": fn, "interval": float(interval), "name": name,
               "stop": threading.Event(), "thread": None,
               "polls": 0, "errors": 0}
        self._pollers.append(rec)
        if self._thread is not None:        # server already serving
            self._start_poller(rec)
        return self

    def _start_poller(self, rec):
        if rec["thread"] is not None:
            return
        if rec["stop"].is_set():            # server re-started post-close
            rec["stop"] = threading.Event()

        def loop():
            while not rec["stop"].wait(rec["interval"]):
                try:
                    rec["fn"]()
                    rec["polls"] += 1
                except Exception:           # noqa: BLE001 — see docstring
                    rec["errors"] += 1
                    _logger.exception("telemetry poller %r failed",
                                      rec["name"])
        rec["thread"] = threading.Thread(
            target=loop, name=f"paddle-tpu-telemetry-{rec['name']}",
            daemon=True)
        rec["thread"].start()

    @property
    def pollers(self) -> list:
        return [{k: r[k] for k in ("name", "interval", "polls", "errors")}
                for r in self._pollers]

    def start(self) -> "TelemetryServer":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.1},
                name="paddle-tpu-telemetry", daemon=True)
            self._thread.start()
        for rec in self._pollers:
            self._start_poller(rec)
        return self

    def close(self):
        for rec in self._pollers:
            rec["stop"].set()
        for rec in self._pollers:
            if rec["thread"] is not None:
                rec["thread"].join(timeout=5.0)
                rec["thread"] = None
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread.join(timeout=5.0)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self) -> "TelemetryServer":
        return self.start()

    def __exit__(self, *exc):
        self.close()
