"""paddle_tpu.obs.probez — active correctness probing (ISSUE 19).

Everything in obs/ so far is PASSIVE: metrics, traces, flight-recorder
captures and the HBM ledger all report how fast and how big — none of
them can see a replica that serves *wrong answers* at perfect latency
(a corrupted KV block, a stale weight after failover, an int8
scale-pool bug, partitioner drift after a jax upgrade). This module is
the active third leg:

  config_fingerprint  deterministic identity of (model config,
                      ServingConfig, jax/jaxlib versions, PADDLE_TPU_*
                      env) — the key goldens are minted under and the
                      thing fleet drift detection compares. Surfaced on
                      every engine's /statusz.

  GoldenStore         host-side pinned golden chains, keyed by
                      (fingerprint, variant). Minted ONCE per
                      model+config fingerprint via the reference
                      `generate_static_ragged` path — the same oracle
                      the engine's bit-identity acceptance tests pin —
                      so identically-configured replicas share goldens.

  Prober              injects golden-canary requests through the REAL
                      serving path (`submit()` + the normal step loop —
                      paged admission, prefix-cache hit AND miss
                      variants, spec decode when configured) and
                      asserts the output chain is BITWISE equal to the
                      pinned golden. Probe requests are tagged
                      end-to-end and excluded from user-facing
                      SLO/latency/goodput accounting; results feed
                      their own `probe_*` metric families. A failure is
                      a first-class structured `{"probe_fail"}` row (a
                      FlightRecorder trigger) naming the variant and
                      first diverging position, with the memz census
                      attached — silent-wrong-answer forensics.

  InvariantAuditor    deep host-side audits on the
                      `TelemetryServer.add_poller` cadence, checking
                      what per-request code paths can't afford to:
                      BlockPool conservation (free + refcounted ≡
                      capacity, trash block never issued), per-owner
                      block lists ≅ refcounts (COW/prefix shares
                      consistent, trie retains included — EXACT
                      accounting), radix-trie ↔ pool cross-check (every
                      device-cached block live, refcounted, off the
                      free list), and int8 scale-pool co-residency.
                      Rendered as `invariant_*` gauges with structured
                      `{"invariant_violation"}` findings on transition.

Threading: the ServingEngine is NOT internally synchronized — when a
poller thread probes while another thread drives submit()/step(), both
must share one lock around every engine call. `Prober(lock=...)` /
`InvariantAuditor(lock=...)` take that shared lock; they default to a
private one (sufficient when the prober is the only concurrent driver,
e.g. probing an otherwise idle replica).
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

__all__ = ["config_fingerprint", "GoldenStore", "Prober",
           "InvariantAuditor"]


# ------------------------------------------------------------ fingerprint

def _json_safe(v):
    """Deterministic JSON coercion: callables/objects hash by qualified
    name, never by repr (a function repr embeds its memory address —
    identical replicas would fingerprint apart)."""
    if isinstance(v, (int, float, str, bool, type(None))):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in sorted(v.items())}
    if callable(v):
        return "callable:" + getattr(v, "__qualname__",
                                     type(v).__name__)
    return f"{type(v).__module__}.{type(v).__name__}"


def config_fingerprint(model_config, serving_config=None,
                       env: Optional[dict] = None) -> dict:
    """Deterministic fingerprint of everything that decides what bytes a
    greedy chain contains: model config, ServingConfig envelope,
    jax/jaxlib versions, and the PADDLE_TPU_* environment. Two replicas
    with equal `sha` must produce bit-identical output for the same
    prompt — which is exactly why goldens are keyed by it and why the
    fleet view flags `config_drift` when members disagree."""
    try:
        import jax
        jax_version = jax.__version__
    except Exception:                       # noqa: BLE001 — stub builds
        jax_version = None
    try:
        import jaxlib
        jaxlib_version = jaxlib.__version__
    except Exception:                       # noqa: BLE001
        jaxlib_version = None
    if env is None:
        env = {k: v for k, v in os.environ.items()
               if k.startswith("PADDLE_TPU_")}
    components = {
        "model": _json_safe(dict(vars(model_config))
                            if not isinstance(model_config, dict)
                            else model_config),
        "serving": _json_safe(dict(vars(serving_config))
                              if serving_config is not None
                              and not isinstance(serving_config, dict)
                              else (serving_config or {})),
        "versions": {"jax": jax_version, "jaxlib": jaxlib_version},
        "env": {k: env[k] for k in sorted(env)},
    }
    blob = json.dumps(components, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return {"sha": hashlib.sha256(blob).hexdigest()[:16],
            "components": components}


# ------------------------------------------------------------ golden store

class GoldenStore:
    """Host-side pinned golden chains keyed by (fingerprint sha,
    variant). One store shared across a fleet's probers means each
    golden is minted ONCE per model+config fingerprint — replicas with
    the same fingerprint ride the same pinned truth, and a replica
    whose fingerprint drifted simply mints (and fails) under its own
    key, which is what makes drift explain probe misses."""

    def __init__(self):
        self._chains: Dict[tuple, np.ndarray] = {}
        self._lock = threading.Lock()
        self.minted_total = 0

    def __len__(self):
        return len(self._chains)

    def get(self, sha: str, variant: str) -> Optional[np.ndarray]:
        with self._lock:
            return self._chains.get((sha, variant))

    def get_or_mint(self, sha: str, variant: str,
                    mint: Callable[[], np.ndarray]) -> np.ndarray:
        with self._lock:
            chain = self._chains.get((sha, variant))
            if chain is None:
                chain = np.asarray(mint(), dtype=np.int64)  # lint: allow(tracer-asarray)
                self._chains[(sha, variant)] = chain
                self.minted_total += 1
        return chain


# ----------------------------------------------------------------- prober

class _VariantState:
    __slots__ = ("prompt", "pass_total", "fail_total", "noise_total",
                 "failing", "last_status", "last_reason",
                 "last_latency_s", "last_divergence", "last_ts")

    def __init__(self, prompt: np.ndarray):
        self.prompt = prompt
        self.pass_total = 0
        self.fail_total = 0
        self.noise_total = 0            # rejected/timeout: prober noise
        self.failing = False
        self.last_status: Optional[str] = None
        self.last_reason: Optional[str] = None
        self.last_latency_s: Optional[float] = None
        self.last_divergence: Optional[int] = None
        self.last_ts: Optional[float] = None

    def to_dict(self) -> dict:
        return {"pass_total": self.pass_total,
                "fail_total": self.fail_total,
                "noise_total": self.noise_total,
                "failing": self.failing,
                "last_status": self.last_status,
                "last_reason": self.last_reason,
                "last_latency_s": self.last_latency_s,
                "first_divergence": self.last_divergence,
                "prompt_tokens": int(self.prompt.shape[0])}


class Prober:
    """Golden-canary correctness sentinel for ONE engine/replica.

    `probe_once()` is one cycle: every variant submits through the real
    `submit()` path (tagged `probe=True`, so user-facing SLO/latency/
    goodput accounting never sees it), rides the normal step loop to a
    terminal status, and its generated chain is compared BITWISE to the
    pinned golden. Per-variant pass/fail is a transition state machine:
    one structured `{"probe_fail"}` row (flight-recorder trigger, memz
    census attached) on entry into failure, one inert `{"probe_clear"}`
    row on recovery — never a row per failing cycle. Rejected/timed-out
    probes (a draining or saturated replica) are prober NOISE, not
    correctness failures.

    Variants adapt to the engine's config so probes cover the
    executables users actually ride:

      decode       always — plain admission + chunked greedy decode
      prefix_miss  prefix_cache engines: a sub-block prompt that can
                   never be cached, so EVERY cycle runs the full
                   prefill miss path
      prefix_hit   prefix_cache engines: a block-aligned pinned prompt —
                   first cycle seeds the trie, every later cycle is the
                   zero-prefill hit + COW path (the path a corrupted
                   cached block breaks)
      spec         spec_decode engines: a block-aligned prompt whose
                   cached chain prompt-lookup-drafts its own future —
                   the verify executable end-to-end

    Call `warm()` during engine warmup: it mints the goldens (the
    reference `generate_static_ragged` executable compiles there) and
    runs one cycle, so steady-state probing adds ZERO jit cache misses.
    """

    def __init__(self, engine, *, store: Optional[GoldenStore] = None,
                 max_new_tokens: Optional[int] = None,
                 replica: Optional[str] = None, seed: int = 1217,
                 max_steps: int = 512, lock=None,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self.store = store if store is not None else GoldenStore()
        self.replica = replica
        self.max_steps = int(max_steps)
        self.lock = lock if lock is not None else threading.Lock()
        self.clock = clock
        self.auditor = None             # serve_telemetry composes one in
        cfg = engine.config
        self.k = cfg.max_new_tokens if max_new_tokens is None \
            else min(int(max_new_tokens), cfg.max_new_tokens)
        self.fingerprint = engine.fingerprint()
        self.cycles_total = 0
        self.failures_total = 0
        self.last_fail: Optional[dict] = None
        self._vstates: Dict[str, _VariantState] = {}
        rng = np.random.RandomState(seed)
        for name, prompt in self._build_variants(cfg, rng):
            self._vstates[name] = _VariantState(prompt)

    # ------------------------------------------------------- construction
    def _build_variants(self, cfg, rng):
        vocab = int(self.engine.model.config.vocab_size)

        def prompt(n):
            return rng.randint(1, vocab, (n,)).astype(np.int64)

        out = [("decode", prompt(max(1, min(cfg.prompt_cap, 8))))]
        if cfg.paged and cfg.prefix_cache:
            bs = cfg.kv_block
            aligned = min(2 * bs, (cfg.prompt_cap // bs) * bs)
            if aligned >= bs:
                # sub-block length: never forms a full block, so the trie
                # never caches it — every cycle is a genuine miss
                out.append(("prefix_miss",
                            prompt(max(1, min(bs - 1, cfg.prompt_cap)))))
                out.append(("prefix_hit", prompt(aligned)))
                if cfg.spec_decode:
                    out.append(("spec", prompt(aligned)))
        return out

    @property
    def variants(self) -> Dict[str, np.ndarray]:
        return {name: st.prompt for name, st in self._vstates.items()}

    @property
    def failing(self) -> bool:
        return any(st.failing for st in self._vstates.values())

    # ------------------------------------------------------------ goldens
    def _mint(self, prompt: np.ndarray) -> np.ndarray:
        """The reference chain: `generate_static_ragged` on the same
        prompt under the engine's exact sampling/dtype envelope — the
        oracle the engine's bit-identity acceptance tests already pin,
        so golden == engine output is the DEFINITION of healthy."""
        cfg = self.engine.config
        cap = int(cfg.prompt_cap)
        ids = np.zeros((1, cap), np.int64)
        ids[0, :prompt.shape[0]] = prompt
        out = self.engine.model.generate_static_ragged(
            ids, [int(prompt.shape[0])], max_new_tokens=self.k,
            temperature=cfg.temperature, top_k=cfg.top_k,
            top_p=cfg.top_p, seed=cfg.seed,
            eos_token_id=cfg.eos_token_id,
            weight_dtype=cfg.weight_dtype, cache_dtype=cfg.cache_dtype)
        return np.asarray(out.numpy())[0, cap:cap + self.k]  # lint: allow(tracer-asarray)

    def golden(self, variant: str) -> np.ndarray:
        st = self._vstates[variant]
        return self.store.get_or_mint(self.fingerprint["sha"], variant,
                                      lambda: self._mint(st.prompt))

    def probe_blocks(self, variant: str = "prefix_hit") -> List[int]:
        """The pool blocks the variant's cached prefix currently maps —
        the blocks a targeted corruption test flips (the next hit-path
        probe attends them and must diverge)."""
        prefix = getattr(self.engine, "_prefix", None)
        if prefix is None or variant not in self._vstates:
            return []
        blocks, _ = prefix.match(self._vstates[variant].prompt)
        return list(blocks)

    def warm(self) -> "Prober":
        """Mint every golden + run TWO cycles: all probe-side
        executables (the reference generator included) lower HERE,
        keeping the steady-state zero-jit-miss invariant intact with
        the prober attached. Two cycles because the first seeds the
        prefix trie (miss-path executables) and only the second rides
        the zero-prefill full-hit admission path."""
        for name in self._vstates:
            self.golden(name)
        self.probe_once()
        self.probe_once()
        return self

    # ------------------------------------------------------------ probing
    def _run_one(self, variant: str, st: _VariantState) -> dict:
        eng = self.engine
        golden = self.golden(variant)
        t0 = self.clock()
        req = eng.submit(st.prompt, max_new_tokens=self.k, probe=True)
        steps = 0
        while req.status in ("queued", "active") and \
                steps < self.max_steps:
            eng.step()
            steps += 1
        latency = self.clock() - t0
        res = {"variant": variant, "status": req.status,
               "reason": req.reason, "latency_s": latency,
               "request": req.id, "steps": steps}
        if req.status == "done":
            tokens = np.asarray(req.tokens, dtype=np.int64)  # lint: allow(tracer-asarray)
            if tokens.shape == golden.shape and \
                    bool(np.array_equal(tokens, golden)):
                res["status"] = "pass"
            else:
                diff = np.nonzero(tokens[:golden.shape[0]] !=
                                  golden[:tokens.shape[0]])[0] \
                    if tokens.shape[0] and golden.shape[0] else np.array([0])
                pos = int(diff[0]) if diff.size else \
                    int(min(tokens.shape[0], golden.shape[0]))
                res.update(status="fail", first_divergence=pos,
                           expected=int(golden[pos])
                           if pos < golden.shape[0] else None,
                           got=int(tokens[pos])
                           if pos < tokens.shape[0] else None)
        elif req.status in ("rejected", "timeout"):
            res["status"] = "noise"     # replica-state refusal, not a
            #                             correctness verdict
        else:                           # "error" / stuck past max_steps:
            # the sentinel cannot confirm correctness — that IS failing
            res["status"] = "fail"
            res["first_divergence"] = None
            if req.status in ("queued", "active"):
                res["reason"] = "stalled"
        return res

    def probe_once(self) -> dict:
        """One full probe cycle over every variant. Fires the
        ``probe.cycle`` chaos site first (corruption faults inject
        here: "detected within one probe cycle" is then exact), runs
        each variant through the real serving path under the shared
        engine lock, and advances the per-variant transition state
        machine."""
        eng = self.engine
        if eng.chaos is not None:
            eng.chaos.fire("probe.cycle", replica=self.replica)
        results = {}
        with self.lock:
            self.cycles_total += 1
            for variant, st in self._vstates.items():
                res = self._run_one(variant, st)
                results[variant] = res
                st.last_status = res["status"]
                st.last_reason = res.get("reason")
                st.last_latency_s = res["latency_s"]
                st.last_ts = time.time()
                if res["status"] == "pass":
                    st.pass_total += 1
                    if st.failing:
                        st.failing = False
                        st.last_divergence = None
                        eng.metrics._emit({"probe_clear":
                                           {"variant": variant,
                                            "replica": self.replica},
                                           "ts": time.time()})
                elif res["status"] == "fail":
                    st.fail_total += 1
                    st.last_divergence = res.get("first_divergence")
                    if not st.failing:
                        st.failing = True
                        self.failures_total += 1
                        self._emit_fail(variant, res)
                else:
                    st.noise_total += 1
        return {"results": results, "failing": self.failing}

    def _emit_fail(self, variant: str, res: dict):
        """The first-class failure event: one structured row on the
        transition into failure — the flight recorder taps it (pinned
        capture), the fleet sees `failing` on the next /probez scrape,
        and the memz census rides along as the forensics snapshot at
        the moment of divergence."""
        eng = self.engine
        body = {"variant": variant, "replica": self.replica,
                "request": res.get("request"),
                "reason": res.get("reason"),
                "first_divergence": res.get("first_divergence"),
                "expected": res.get("expected"),
                "got": res.get("got"),
                "fingerprint": self.fingerprint["sha"]}
        memz = getattr(eng, "_memz", None)
        if memz is not None:
            try:
                body["memz_census"] = memz.census()
            except Exception:           # noqa: BLE001 — forensics must
                pass                    # never mask the failure itself
        self.last_fail = dict(body, ts=time.time())
        eng.metrics._emit({"probe_fail": body, "ts": time.time()})

    # ---------------------------------------------------------- reporting
    def probez(self, _query: Optional[dict] = None) -> dict:
        """The /probez payload: overall state, per-variant sentinel
        detail, golden/fingerprint identity, and the invariant auditor's
        summary when one rides along."""
        if not self._vstates:
            state = "idle"
        elif self.failing:
            state = "failing"
        elif any(st.pass_total for st in self._vstates.values()):
            state = "passing"
        else:
            state = "idle"
        out = {"state": state,
               "replica": self.replica,
               "fingerprint": self.fingerprint["sha"],
               "cycles_total": self.cycles_total,
               "failures_total": self.failures_total,
               "goldens": len(self.store),
               "max_new_tokens": self.k,
               "variants": {n: st.to_dict()
                            for n, st in self._vstates.items()}}
        if self.last_fail is not None:
            out["last_fail"] = {k: v for k, v in self.last_fail.items()
                                if k != "memz_census"}
        if self.auditor is not None:
            out["invariants"] = self.auditor.summary()
        return out

    def metrics_text(self, prefix: str = "paddle_tpu_probe") -> str:
        """The probe_* families — deliberately a SEPARATE producer from
        ServingMetrics: a no-prober replica's user-facing exposition is
        byte-identical by construction (the probe/SLO isolation
        guarantee is structural, not subtractive)."""
        p = prefix
        items = sorted(self._vstates.items())
        lines = [f"# HELP {p}_pass_total probe cycles whose chain "
                 f"matched the pinned golden bitwise",
                 f"# TYPE {p}_pass_total counter"]
        lines += [f'{p}_pass_total{{variant="{n}"}} {st.pass_total}'
                  for n, st in items]
        lines += [f"# HELP {p}_fail_total probe cycles that diverged "
                  f"from the golden (or could not complete)",
                  f"# TYPE {p}_fail_total counter"]
        lines += [f'{p}_fail_total{{variant="{n}"}} {st.fail_total}'
                  for n, st in items]
        lines += [f"# HELP {p}_noise_total probes rejected/expired by "
                  f"replica state (draining/overload) — not verdicts",
                  f"# TYPE {p}_noise_total counter"]
        lines += [f'{p}_noise_total{{variant="{n}"}} {st.noise_total}'
                  for n, st in items]
        lat = [(n, st.last_latency_s) for n, st in items
               if st.last_latency_s is not None]
        if lat:
            lines += [f"# HELP {p}_last_latency_seconds wall time of "
                      f"the variant's most recent probe",
                      f"# TYPE {p}_last_latency_seconds gauge"]
            lines += [f'{p}_last_latency_seconds{{variant="{n}"}} '
                      f'{v:.6g}' for n, v in lat]
        lines += [f"# HELP {p}_failing replica currently failing "
                  f"correctness probes (the router ejection signal)",
                  f"# TYPE {p}_failing gauge",
                  f"{p}_failing {1 if self.failing else 0}",
                  f"# HELP {p}_cycles_total probe cycles run",
                  f"# TYPE {p}_cycles_total counter",
                  f"{p}_cycles_total {self.cycles_total}"]
        return "\n".join(lines) + "\n"


# ------------------------------------------------------ invariant auditor

class InvariantAuditor:
    """Deep host-side invariant audits over one paged engine — the
    checks per-request code paths can't afford to run, scheduled on the
    `TelemetryServer.add_poller` cadence (or driven synchronously).

    Checks (all pure host reads — an audit never syncs the device):

      pool_conservation   free + refcounted ≡ capacity_blocks, the free
                          list and refcount table are disjoint, and the
                          trash block (0) was never issued
      owner_refcounts     EXACT accounting: every block's refcount ==
                          its occurrences across per-owner row lists +
                          its device trie nodes — COW/prefix shares and
                          trie retains all reconciled
      trie_pool           every device-cached trie node maps a live
                          block: non-trash, absent from the free list,
                          refcount >= 1; device-node count matches the
                          cache's own counter
      scale_coresidency   int8 pools: every layer's scale planes match
                          their code planes' geometry (scales shard,
                          spill and COW WITH their codes or quantized
                          attention reads garbage)

    Violations are transition events: one `{"invariant_violation"}`
    structured row (flight-recorder trigger) when a check flips to
    violating, one inert `{"invariant_clear"}` on recovery. A check
    that trips is re-run once before it counts — the audit may race a
    concurrent engine step when no shared lock is passed, and real
    violations persist while mid-step transients vanish."""

    CHECKS = ("pool_conservation", "owner_refcounts", "trie_pool",
              "scale_coresidency")

    def __init__(self, engine, *, lock=None):
        self.engine = engine
        self.lock = lock if lock is not None else threading.Lock()
        self.audits_total = 0
        self.violations_total = 0
        self.skipped_total = 0
        self._ok = {c: True for c in self.CHECKS}
        self.findings: List[dict] = []      # bounded recent violations

    # ------------------------------------------------------------ checks
    def _check_pool_conservation(self, pool) -> List[str]:
        bad = []
        free, refs = list(pool._free), dict(pool._refs)
        if len(free) + len(refs) != pool.capacity_blocks:
            bad.append(f"free({len(free)}) + refcounted({len(refs)}) "
                       f"!= capacity({pool.capacity_blocks})")
        overlap = set(free) & set(refs)
        if overlap:
            bad.append(f"blocks both free and refcounted: "
                       f"{sorted(overlap)[:8]}")
        if 0 in refs or 0 in free:
            bad.append("trash block 0 was issued")
        return bad

    def _trie_device_blocks(self, prefix) -> List[int]:
        blocks = []
        stack = list(prefix._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            if n.block >= 1:            # SPILLED (-1) lives on the host
                blocks.append(n.block)
        return blocks

    def _check_owner_refcounts(self, pool, prefix) -> List[str]:
        bad = []
        expected: Dict[int, int] = {}
        for owner, row in list(pool._rows.items()):
            for b in list(row):
                expected[b] = expected.get(b, 0) + 1
        if prefix is not None:
            for b in self._trie_device_blocks(prefix):
                expected[b] = expected.get(b, 0) + 1
        refs = dict(pool._refs)
        for b, want in expected.items():
            have = refs.get(b, 0)
            if have != want:
                bad.append(f"block {b}: refcount {have} != "
                           f"{want} (rows + trie)")
        for b in refs:
            if b not in expected:
                bad.append(f"block {b}: refcount {refs[b]} with no "
                           f"owner row or trie node")
        return bad[:8]

    def _check_trie_pool(self, pool, prefix) -> List[str]:
        if prefix is None:
            return []
        bad = []
        free = set(pool._free)
        device = self._trie_device_blocks(prefix)
        for b in device:
            if b in free:
                bad.append(f"trie block {b} is on the free list")
            if pool.refcount(b) < 1:
                bad.append(f"trie block {b} has refcount "
                           f"{pool.refcount(b)}")
        if len(device) != prefix.cached_blocks:
            bad.append(f"trie walk found {len(device)} device blocks, "
                       f"cache counter says {prefix.cached_blocks}")
        return bad[:8]

    def _check_scale_coresidency(self, pool, pools) -> List[str]:
        if pool.cache_dtype != "int8" or pools is None:
            return []
        bad = []
        for i, layer in enumerate(pools):
            if len(layer) != 4:
                bad.append(f"layer {i}: int8 pool tuple has "
                           f"{len(layer)} planes, want 4")
                continue
            kc, ks, vc, vs = layer
            for tag, codes, scales in (("k", kc, ks), ("v", vc, vs)):
                if str(codes.dtype) != "int8":
                    bad.append(f"layer {i} {tag}-codes dtype "
                               f"{codes.dtype}")
                if tuple(scales.shape) != tuple(codes.shape[:-1]):
                    bad.append(f"layer {i} {tag}-scales shape "
                               f"{tuple(scales.shape)} does not cover "
                               f"codes {tuple(codes.shape)}")
                if codes.shape[0] != pool.num_blocks:
                    bad.append(f"layer {i} {tag}-codes holds "
                               f"{codes.shape[0]} blocks, pool has "
                               f"{pool.num_blocks}")
        return bad[:8]

    def _run_checks(self) -> Dict[str, List[str]]:
        eng = self.engine
        if not eng.config.paged:
            return {c: [] for c in self.CHECKS}
        pool = eng._pool
        prefix = getattr(eng, "_prefix", None)
        pools = getattr(eng, "_pools", None)
        return {
            "pool_conservation": self._check_pool_conservation(pool),
            "owner_refcounts": self._check_owner_refcounts(pool, prefix),
            "trie_pool": self._check_trie_pool(pool, prefix),
            "scale_coresidency": self._check_scale_coresidency(pool,
                                                               pools),
        }

    # ------------------------------------------------------------- audit
    def audit(self) -> dict:
        """One audit pass; the poller entry point. Returns the summary
        (also served inside /probez)."""
        with self.lock:
            try:
                found = self._run_checks()
                if any(found.values()):
                    # double-check: a lock-free audit can race one
                    # engine step mid-mutation; real violations persist
                    found = self._run_checks()
            except RuntimeError:
                # host dict resized under the walk — skip this cycle,
                # the next one sees a quiescent snapshot
                self.skipped_total += 1
                return self.summary()
            self.audits_total += 1
            for check, bad in found.items():
                if bad and self._ok[check]:
                    self._ok[check] = False
                    self.violations_total += 1
                    body = {"check": check, "detail": bad}
                    self.findings.append(dict(body, ts=time.time()))
                    del self.findings[:-64]
                    self.engine.metrics._emit(
                        {"invariant_violation": body,
                         "ts": time.time()})
                elif not bad and not self._ok[check]:
                    self._ok[check] = True
                    self.engine.metrics._emit(
                        {"invariant_clear": {"check": check},
                         "ts": time.time()})
        return self.summary()

    @property
    def violating(self) -> bool:
        return not all(self._ok.values())

    def summary(self) -> dict:
        return {"ok": dict(self._ok),
                "violating": self.violating,
                "audits_total": self.audits_total,
                "violations_total": self.violations_total,
                "skipped_total": self.skipped_total,
                "findings": self.findings[-4:]}

    def metrics_text(self, prefix: str = "paddle_tpu_invariant") -> str:
        p = prefix
        lines = [f"# HELP {p}_ok deep invariant check currently "
                 f"holding (0 = violated)",
                 f"# TYPE {p}_ok gauge"]
        lines += [f'{p}_ok{{check="{c}"}} {1 if ok else 0}'
                  for c, ok in sorted(self._ok.items())]
        lines += [f"# HELP {p}_audits_total audit passes completed",
                  f"# TYPE {p}_audits_total counter",
                  f"{p}_audits_total {self.audits_total}",
                  f"# HELP {p}_violations_total checks that flipped "
                  f"into violation",
                  f"# TYPE {p}_violations_total counter",
                  f"{p}_violations_total {self.violations_total}",
                  f"# HELP {p}_skipped_total audit passes skipped on a "
                  f"concurrent-mutation race",
                  f"# TYPE {p}_skipped_total counter",
                  f"{p}_skipped_total {self.skipped_total}"]
        return "\n".join(lines) + "\n"
