/* C inference API for paddle_tpu exported models.
 *
 * Reference capability: paddle/fluid/inference/capi_exp/pd_inference_api.h
 * (the plain-C predictor ABI). Artifact: the StableHLO export written by
 * paddle_tpu.jit.save / static.save_inference_model (<path>.pdmodel +
 * .pdmeta + .pdparams).
 *
 * Link against libptinfer.so (built by paddle_tpu.io.native.build_infer_capi
 * or the g++ line in predictor_capi.cc). The library embeds a Python
 * interpreter to host the XLA runtime; callers see only this C surface.
 *
 * Dtype codes (PD_TensorCopyFromCpu): 0 = float32, 1 = int64, 2 = int32.
 */
#ifndef PT_INFERENCE_API_H_
#define PT_INFERENCE_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Config PD_Config;
typedef struct PD_Predictor PD_Predictor;
typedef struct PD_Tensor PD_Tensor;

PD_Config* PD_ConfigCreate(void);
void PD_ConfigSetModel(PD_Config*, const char* prog_file,
                       const char* params_file);
void PD_ConfigDestroy(PD_Config*);

PD_Predictor* PD_PredictorCreate(PD_Config*);
void PD_PredictorDestroy(PD_Predictor*);
size_t PD_PredictorGetInputNum(PD_Predictor*);
size_t PD_PredictorGetOutputNum(PD_Predictor*);
/* returned strings are malloc'd; caller frees with free() */
char* PD_PredictorGetInputName(PD_Predictor*, size_t idx);
char* PD_PredictorGetOutputName(PD_Predictor*, size_t idx);
PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor*, const char* name);
PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor*, const char* name);
/* returns 1 on success */
int PD_PredictorRun(PD_Predictor*);

void PD_TensorDestroy(PD_Tensor*);
void PD_TensorReshape(PD_Tensor*, size_t ndim, const int32_t* shape);
size_t PD_TensorGetNumel(PD_Tensor*);
size_t PD_TensorGetShape(PD_Tensor*, int32_t* shape_out, size_t max_ndim);
int PD_TensorCopyFromCpu(PD_Tensor*, const void* data, int dtype);
int PD_TensorCopyToCpu(PD_Tensor*, void* data, size_t nbytes);

#ifdef __cplusplus
}
#endif
#endif /* PT_INFERENCE_API_H_ */
