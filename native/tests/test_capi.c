/* C tests for the inference ABI (reference: capi_exp test programs).
 *
 * Usage:
 *   test_capi <model_prefix>           happy path: feed ones, print first
 *   test_capi <model_prefix> errors    error paths: bad artifact path, bad
 *                                      handle names, undersized output
 *                                      buffer, NULL destroys — all must
 *                                      fail SOFTLY (NULL/0), never crash
 *   test_capi <model_prefix> multiio   two inputs / two outputs by name,
 *                                      prints sum0=… sum1=…
 * Compiled and driven by tests/test_inference_capi.py.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pt_inference_api.h"

static PD_Predictor* make_pred(const char* prefix) {
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, prefix, "");
  PD_Predictor* pred = PD_PredictorCreate(cfg);
  PD_ConfigDestroy(cfg);
  return pred;
}

static int run_happy(const char* prefix) {
  PD_Predictor* pred = make_pred(prefix);
  if (!pred) {
    fprintf(stderr, "predictor create failed\n");
    return 1;
  }
  if (PD_PredictorGetInputNum(pred) < 1) {
    fprintf(stderr, "no inputs\n");
    return 1;
  }
  char* in_name = PD_PredictorGetInputName(pred, 0);
  PD_Tensor* in = PD_PredictorGetInputHandle(pred, in_name);
  size_t numel = PD_TensorGetNumel(in);
  float* buf = (float*)malloc(numel * sizeof(float));
  for (size_t i = 0; i < numel; ++i) buf[i] = 1.0f;
  if (!PD_TensorCopyFromCpu(in, buf, 0)) {
    fprintf(stderr, "copy_from failed\n");
    return 1;
  }
  if (!PD_PredictorRun(pred)) {
    fprintf(stderr, "run failed\n");
    return 1;
  }
  char* out_name = PD_PredictorGetOutputName(pred, 0);
  PD_Tensor* out = PD_PredictorGetOutputHandle(pred, out_name);
  size_t onumel = PD_TensorGetNumel(out);
  float* obuf = (float*)malloc(onumel * sizeof(float));
  if (!PD_TensorCopyToCpu(out, obuf, onumel * sizeof(float))) {
    fprintf(stderr, "copy_to failed\n");
    return 1;
  }
  printf("in=%s numel=%zu out=%s numel=%zu first=%.6f\n", in_name, numel,
         out_name, onumel, (double)obuf[0]);
  free(buf);
  free(obuf);
  free(in_name);
  free(out_name);
  PD_TensorDestroy(in);
  PD_TensorDestroy(out);
  PD_PredictorDestroy(pred);
  return 0;
}

static int run_errors(const char* prefix) {
  /* 1) missing artifact: create must return NULL, not crash */
  PD_Predictor* bad = make_pred("/nonexistent/definitely_missing_model");
  if (bad != NULL) {
    fprintf(stderr, "ERR: create on missing artifact returned non-NULL\n");
    return 1;
  }
  /* 2) NULL destroys are no-ops */
  PD_PredictorDestroy(NULL);
  PD_TensorDestroy(NULL);

  /* 3) the ABI stays usable after a failed create (no poisoned
     interpreter error state) */
  PD_Predictor* pred = make_pred(prefix);
  if (!pred) {
    fprintf(stderr, "ERR: good artifact failed after bad create\n");
    return 1;
  }
  /* 4) unknown tensor names return NULL */
  if (PD_PredictorGetInputHandle(pred, "no_such_input") != NULL ||
      PD_PredictorGetOutputHandle(pred, "no_such_output") != NULL) {
    fprintf(stderr, "ERR: unknown handle name returned non-NULL\n");
    return 1;
  }
  /* 5) out-of-range name index returns NULL */
  if (PD_PredictorGetInputName(pred, 9999) != NULL) {
    fprintf(stderr, "ERR: out-of-range input name returned non-NULL\n");
    return 1;
  }
  /* 6) undersized output buffer: CopyToCpu must refuse (return 0) and
     leave the buffer guard untouched */
  char* in_name = PD_PredictorGetInputName(pred, 0);
  PD_Tensor* in = PD_PredictorGetInputHandle(pred, in_name);
  size_t numel = PD_TensorGetNumel(in);
  float* buf = (float*)malloc(numel * sizeof(float));
  for (size_t j = 0; j < numel; ++j) buf[j] = 1.0f;
  PD_TensorCopyFromCpu(in, buf, 0);
  PD_PredictorRun(pred);
  char* out_name = PD_PredictorGetOutputName(pred, 0);
  PD_Tensor* out = PD_PredictorGetOutputHandle(pred, out_name);
  unsigned char tiny[2] = {0xAB, 0xCD};
  if (PD_TensorCopyToCpu(out, tiny, 1) != 0) {
    fprintf(stderr, "ERR: undersized copy_to reported success\n");
    return 1;
  }
  if (tiny[1] != 0xCD) {
    fprintf(stderr, "ERR: undersized copy_to wrote past the buffer\n");
    return 1;
  }
  /* 7) the predictor still works after all the failed calls */
  float* obuf = (float*)malloc(PD_TensorGetNumel(out) * sizeof(float));
  if (!PD_TensorCopyToCpu(out, obuf,
                          PD_TensorGetNumel(out) * sizeof(float))) {
    fprintf(stderr, "ERR: valid copy_to failed after error-path calls\n");
    return 1;
  }
  printf("errors_ok first=%.6f\n", (double)obuf[0]);
  free(buf);
  free(obuf);
  free(in_name);
  free(out_name);
  PD_TensorDestroy(in);
  PD_TensorDestroy(out);
  PD_PredictorDestroy(pred);
  return 0;
}

static int run_multiio(const char* prefix) {
  PD_Predictor* pred = make_pred(prefix);
  if (!pred) {
    fprintf(stderr, "predictor create failed\n");
    return 1;
  }
  size_t nin = PD_PredictorGetInputNum(pred);
  size_t nout = PD_PredictorGetOutputNum(pred);
  if (nin != 2 || nout != 2) {
    fprintf(stderr, "expected 2x2 io, got %zux%zu\n", nin, nout);
    return 1;
  }
  for (size_t i = 0; i < nin; ++i) {
    char* name = PD_PredictorGetInputName(pred, i);
    PD_Tensor* t = PD_PredictorGetInputHandle(pred, name);
    size_t numel = PD_TensorGetNumel(t);
    float* buf = (float*)malloc(numel * sizeof(float));
    for (size_t j = 0; j < numel; ++j) buf[j] = (float)(i + 1);
    if (!PD_TensorCopyFromCpu(t, buf, 0)) {
      fprintf(stderr, "copy_from input %zu failed\n", i);
      return 1;
    }
    free(buf);
    free(name);
    PD_TensorDestroy(t);
  }
  if (!PD_PredictorRun(pred)) {
    fprintf(stderr, "run failed\n");
    return 1;
  }
  double sums[2] = {0, 0};
  for (size_t i = 0; i < nout; ++i) {
    char* name = PD_PredictorGetOutputName(pred, i);
    PD_Tensor* t = PD_PredictorGetOutputHandle(pred, name);
    size_t numel = PD_TensorGetNumel(t);
    float* buf = (float*)malloc(numel * sizeof(float));
    if (!PD_TensorCopyToCpu(t, buf, numel * sizeof(float))) {
      fprintf(stderr, "copy_to output %zu failed\n", i);
      return 1;
    }
    for (size_t j = 0; j < numel; ++j) sums[i] += (double)buf[j];
    free(buf);
    free(name);
    PD_TensorDestroy(t);
  }
  printf("sum0=%.6f sum1=%.6f\n", sums[0], sums[1]);
  PD_PredictorDestroy(pred);
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_prefix> [errors|multiio]\n", argv[0]);
    return 2;
  }
  if (argc >= 3 && strcmp(argv[2], "errors") == 0) return run_errors(argv[1]);
  if (argc >= 3 && strcmp(argv[2], "multiio") == 0)
    return run_multiio(argv[1]);
  return run_happy(argv[1]);
}
