/* C smoke test for the inference ABI (reference: capi_exp test programs).
 *
 * Usage: test_capi <model_path_prefix>
 * Loads <prefix>.pdmodel/.pdmeta, feeds ones, runs, prints the first few
 * output values, exits 0 on success. Compiled and driven by
 * tests/test_inference_capi.py.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pt_inference_api.h"

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <model_prefix>\n", argv[0]);
    return 2;
  }
  PD_Config* cfg = PD_ConfigCreate();
  PD_ConfigSetModel(cfg, argv[1], "");
  PD_Predictor* pred = PD_PredictorCreate(cfg);
  if (!pred) {
    fprintf(stderr, "predictor create failed\n");
    return 1;
  }
  size_t nin = PD_PredictorGetInputNum(pred);
  if (nin < 1) {
    fprintf(stderr, "no inputs\n");
    return 1;
  }
  char* in_name = PD_PredictorGetInputName(pred, 0);
  PD_Tensor* in = PD_PredictorGetInputHandle(pred, in_name);
  size_t numel = PD_TensorGetNumel(in);
  float* buf = (float*)malloc(numel * sizeof(float));
  for (size_t i = 0; i < numel; ++i) buf[i] = 1.0f;
  if (!PD_TensorCopyFromCpu(in, buf, 0)) {
    fprintf(stderr, "copy_from failed\n");
    return 1;
  }
  if (!PD_PredictorRun(pred)) {
    fprintf(stderr, "run failed\n");
    return 1;
  }
  char* out_name = PD_PredictorGetOutputName(pred, 0);
  PD_Tensor* out = PD_PredictorGetOutputHandle(pred, out_name);
  size_t onumel = PD_TensorGetNumel(out);
  float* obuf = (float*)malloc(onumel * sizeof(float));
  if (!PD_TensorCopyToCpu(out, obuf, onumel * sizeof(float))) {
    fprintf(stderr, "copy_to failed\n");
    return 1;
  }
  printf("in=%s numel=%zu out=%s numel=%zu first=%.6f\n", in_name, numel,
         out_name, onumel, (double)obuf[0]);
  free(buf);
  free(obuf);
  free(in_name);
  free(out_name);
  PD_TensorDestroy(in);
  PD_TensorDestroy(out);
  PD_PredictorDestroy(pred);
  PD_ConfigDestroy(cfg);
  return 0;
}
