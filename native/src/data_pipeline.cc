// Native data-pipeline core: blocking batch queue + mmap record readers.
//
// TPU-native counterpart of the reference's C++ data layer (SURVEY §2.1
// "Data pipeline (C++)"): framework/data_feed.cc (file readers feeding
// training threads through a BlockingQueue<std::vector<Record>>),
// framework/blocking_queue.h, and imperative/data_loader.cc (the
// multiprocess DataLoader's C++ side). On TPU the consumer is the host
// input pipeline that keeps jax.device_put fed between steps; the hot
// properties are the same as the reference's: no GIL on the producer side,
// bounded memory, many reader threads per file shard.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
// Memory protocol: the queue owns copies of pushed payloads; pop hands the
// consumer a malloc'd buffer it must free via pt_buffer_free (the Python
// wrapper copies into numpy then frees immediately).
//
// Record file format ("PTR1"): magic(4) | u64 count | count x (u64 len |
// bytes). Writers live in Python (paddle_tpu/io/native.py); readers here
// mmap the file, so record payloads are served zero-copy from page cache.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- queue
struct PtBuffer {
  uint8_t* data;
  uint64_t size;
};

struct PtQueue {
  std::deque<PtBuffer> items;
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  size_t capacity;
  std::atomic<bool> closed{false};
};

PtQueue* pt_queue_create(uint64_t capacity) {
  auto* q = new PtQueue();
  q->capacity = capacity ? capacity : 1;
  return q;
}

// push copies [data, data+size); blocks while full; returns 0 ok, -1 closed
int pt_queue_push(PtQueue* q, const uint8_t* data, uint64_t size) {
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [q] { return q->items.size() < q->capacity ||
                                    q->closed.load(); });
  if (q->closed.load()) return -1;
  uint8_t* copy = static_cast<uint8_t*>(std::malloc(size));
  if (!copy && size) return -2;
  std::memcpy(copy, data, size);
  q->items.push_back(PtBuffer{copy, size});
  lk.unlock();
  q->not_empty.notify_one();
  return 0;
}

// pop blocks until an item or close+drained; returns 0 ok, -1 drained-closed
int pt_queue_pop(PtQueue* q, uint8_t** out_data, uint64_t* out_size) {
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [q] { return !q->items.empty() || q->closed.load(); });
  if (q->items.empty()) return -1;
  PtBuffer b = q->items.front();
  q->items.pop_front();
  lk.unlock();
  q->not_full.notify_one();
  *out_data = b.data;
  *out_size = b.size;
  return 0;
}

uint64_t pt_queue_size(PtQueue* q) {
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

void pt_queue_close(PtQueue* q) {
  q->closed.store(true);
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

// contract: destroy only after readers joined (pt_reader_stop)
void pt_queue_destroy(PtQueue* q) {
  pt_queue_close(q);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (auto& b : q->items) std::free(b.data);
    q->items.clear();
  }
  delete q;
}

void pt_buffer_free(uint8_t* data) { std::free(data); }

// ---------------------------------------------------------------- reader
struct PtRecordFile {
  int fd = -1;
  uint8_t* map = nullptr;
  uint64_t map_size = 0;
  uint64_t count = 0;
  std::vector<std::pair<const uint8_t*, uint64_t>> records;
};

// open + index a PTR1 file; returns nullptr on failure
PtRecordFile* pt_records_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 12) { ::close(fd); return nullptr; }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) { ::close(fd); return nullptr; }
  auto* f = new PtRecordFile();
  f->fd = fd;
  f->map = static_cast<uint8_t*>(map);
  f->map_size = st.st_size;
  if (std::memcmp(f->map, "PTR1", 4) != 0) {
    munmap(map, st.st_size); ::close(fd); delete f; return nullptr;
  }
  uint64_t count;
  std::memcpy(&count, f->map + 4, 8);
  const uint8_t* p = f->map + 12;
  const uint8_t* end = f->map + f->map_size;
  f->records.reserve(count);
  for (uint64_t i = 0; i < count && p + 8 <= end; ++i) {
    uint64_t len;
    std::memcpy(&len, p, 8);
    p += 8;
    if (p + len > end) break;
    f->records.emplace_back(p, len);
    p += len;
  }
  f->count = f->records.size();
  return f;
}

uint64_t pt_records_count(PtRecordFile* f) { return f->count; }

// zero-copy view of record i (valid while file open)
int pt_records_get(PtRecordFile* f, uint64_t i, const uint8_t** data,
                   uint64_t* size) {
  if (i >= f->count) return -1;
  *data = f->records[i].first;
  *size = f->records[i].second;
  return 0;
}

void pt_records_close(PtRecordFile* f) {
  if (!f) return;
  if (f->map) munmap(f->map, f->map_size);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

// --------------------------------------------------- threaded prefetcher
// Readers stride the record index space (rank/world sharding like the
// reference's DataFeed file-list split) and push payloads into the queue.
struct PtReader {
  PtRecordFile* file;
  PtQueue* queue;
  std::vector<std::thread> threads;
  std::atomic<uint64_t> cursor{0};
  std::atomic<bool> stop{false};
  uint64_t begin = 0, end = 0, epochs = 1;
};

static void reader_loop(PtReader* r) {
  // one shared index space of epochs*span items; threads race on the atomic
  // cursor, so records interleave across threads (order is not preserved —
  // same contract as the reference's multi-thread DataFeed)
  const uint64_t span = r->end - r->begin;
  const uint64_t total = r->epochs * span;
  while (!r->stop.load()) {
    uint64_t i = r->cursor.fetch_add(1);
    if (i >= total) break;
    uint64_t idx = r->begin + (i % span);
    const uint8_t* data; uint64_t size;
    if (pt_records_get(r->file, idx, &data, &size) != 0) break;
    if (pt_queue_push(r->queue, data, size) != 0) return;  // queue closed
  }
}

// begin/end: this worker's shard [begin, end); n_threads readers share it
PtReader* pt_reader_start(PtRecordFile* f, PtQueue* q, uint64_t begin,
                          uint64_t end, uint64_t n_threads, uint64_t epochs) {
  auto* r = new PtReader();
  r->file = f;
  r->queue = q;
  r->begin = begin;
  r->end = end > f->count ? f->count : end;
  r->epochs = epochs ? epochs : 1;
  if (n_threads == 0) n_threads = 1;
  for (uint64_t t = 0; t < n_threads; ++t)
    r->threads.emplace_back(reader_loop, r);
  return r;
}

void pt_reader_stop(PtReader* r) {
  r->stop.store(true);
  pt_queue_close(r->queue);
  for (auto& t : r->threads)
    if (t.joinable()) t.join();
  delete r;
}

// done when all records of all epochs pushed (cursor past total span)
int pt_reader_done(PtReader* r) {
  uint64_t span = r->end - r->begin;
  return r->cursor.load() >= r->epochs * span ? 1 : 0;
}

}  // extern "C"
