// Native TCP key-value store master daemon.
//
// Reference analog: paddle/phi/core/distributed/store/tcp_store.cc
// (MasterDaemon :45) — the rendezvous KV server used for multi-host
// bootstrap, launch sign-in, elastic heartbeats and user barriers. The
// Python client (paddle_tpu/distributed/store.py TCPStore) speaks the same
// newline protocol to this daemon; the daemon itself runs GIL-free so
// hundreds of clients (big pods signing in) never contend with the trainer
// process's Python threads.
//
// Design: ONE poll(2)-driven event-loop thread, no thread-per-connection.
// WAIT long-polls are parked connections with a deadline; every mutation
// (SET/ADD/DEL) re-scans parked waiters. A self-pipe wakes the loop for
// shutdown.
//
// Protocol (UTF-8 lines):  CMD key [value]\n
//   SET k v -> OK            GET k  -> OK v | MISSING
//   ADD k n -> OK total      WAIT k t -> OK v | TIMEOUT
//   DEL k   -> OK            KEYS p -> OK k1,k2,...
//   PING    -> OK PONG       else   -> ERR unknown

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cctype>
#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Conn {
  int fd = -1;
  std::string inbuf;
  std::string outbuf;
  bool waiting = false;
  std::string wait_key;
  Clock::time_point wait_deadline;
};

struct Server {
  int listen_fd = -1;
  int wake_r = -1, wake_w = -1;  // self-pipe
  int port = 0;
  std::thread loop;
  std::atomic<bool> stop{false};
  std::unordered_map<int, std::unique_ptr<Conn>> conns;
  std::map<std::string, std::string> kv;  // ordered: prefix scans for KEYS

  ~Server() { shutdown(); }

  void shutdown() {
    if (loop.joinable()) {
      stop.store(true);
      char b = 1;
      (void)!write(wake_w, &b, 1);
      loop.join();
    }
    for (auto& [fd, c] : conns) close(fd);
    conns.clear();
    if (listen_fd >= 0) close(listen_fd), listen_fd = -1;
    if (wake_r >= 0) close(wake_r), wake_r = -1;
    if (wake_w >= 0) close(wake_w), wake_w = -1;
  }

  static void set_nonblock(int fd) {
    int fl = fcntl(fd, F_GETFL, 0);
    fcntl(fd, F_SETFL, fl | O_NONBLOCK);
  }

  bool listen_on(const char* host, int port_in) {
    listen_fd = socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd < 0) return false;
    int one = 1;
    setsockopt(listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port_in));
    if (!host || !*host) {
      addr.sin_addr.s_addr = INADDR_ANY;
    } else if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
      // hostname, not a dotted quad: resolve it — NEVER widen to INADDR_ANY
      // on failure (a 'localhost' store must not listen on every interface)
      addrinfo hints{}, *res = nullptr;
      hints.ai_family = AF_INET;
      hints.ai_socktype = SOCK_STREAM;
      if (getaddrinfo(host, nullptr, &hints, &res) != 0 || !res) return false;
      addr.sin_addr = reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr;
      freeaddrinfo(res);
    }
    if (bind(listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0)
      return false;
    if (listen(listen_fd, 512) < 0) return false;
    socklen_t len = sizeof(addr);
    getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
    port = ntohs(addr.sin_port);
    set_nonblock(listen_fd);
    int pfd[2];
    if (pipe(pfd) < 0) return false;
    wake_r = pfd[0];
    wake_w = pfd[1];
    set_nonblock(wake_r);
    return true;
  }

  void reply(Conn* c, const std::string& s) {
    c->outbuf += s;
    c->outbuf += '\n';
  }

  // Serve a parked WAIT if its key now exists. Returns true when unparked.
  bool try_serve_wait(Conn* c) {
    auto it = kv.find(c->wait_key);
    if (it != kv.end()) {
      c->waiting = false;
      reply(c, "OK " + it->second);
      return true;
    }
    if (Clock::now() >= c->wait_deadline) {
      c->waiting = false;
      reply(c, "TIMEOUT");
      return true;
    }
    return false;
  }

  void on_mutation() {
    for (auto& [fd, c] : conns)
      if (c->waiting) try_serve_wait(c.get());
  }

  void handle_line(Conn* c, const std::string& line) {
    // split into at most 3 fields
    std::string f[3];
    size_t start = 0;
    for (int i = 0; i < 3; ++i) {
      if (start > line.size()) break;
      size_t sp = (i < 2) ? line.find(' ', start) : std::string::npos;
      f[i] = line.substr(start, sp == std::string::npos ? std::string::npos
                                                        : sp - start);
      if (sp == std::string::npos) { start = line.size() + 1; break; }
      start = sp + 1;
    }
    std::string& cmd = f[0];
    for (auto& ch : cmd) ch = static_cast<char>(toupper(ch));

    if (cmd == "SET") {
      kv[f[1]] = f[2];
      reply(c, "OK");
      on_mutation();
    } else if (cmd == "GET") {
      auto it = kv.find(f[1]);
      reply(c, it == kv.end() ? "MISSING" : "OK " + it->second);
    } else if (cmd == "ADD") {
      long n = 1;
      if (!f[2].empty()) n = strtol(f[2].c_str(), nullptr, 10);
      long cur = 0;
      auto it = kv.find(f[1]);
      if (it != kv.end()) cur = strtol(it->second.c_str(), nullptr, 10);
      cur += n;
      kv[f[1]] = std::to_string(cur);
      reply(c, "OK " + std::to_string(cur));
      on_mutation();
    } else if (cmd == "WAIT") {
      double timeout = 300.0;
      if (!f[2].empty()) timeout = strtod(f[2].c_str(), nullptr);
      c->waiting = true;
      c->wait_key = f[1];
      c->wait_deadline =
          Clock::now() + std::chrono::milliseconds(
                             static_cast<long>(timeout * 1000.0));
      try_serve_wait(c);  // answer immediately when the key already exists
    } else if (cmd == "DEL") {
      kv.erase(f[1]);
      reply(c, "OK");
      on_mutation();
    } else if (cmd == "KEYS") {
      std::string out = "OK ";
      bool first = true;
      for (auto it = kv.lower_bound(f[1]); it != kv.end(); ++it) {
        if (it->first.compare(0, f[1].size(), f[1]) != 0) break;
        if (!first) out += ',';
        out += it->first;
        first = false;
      }
      reply(c, out);
    } else if (cmd == "PING") {
      reply(c, "OK PONG");
    } else {
      reply(c, "ERR unknown");
    }
  }

  void drop(int fd) {
    close(fd);
    conns.erase(fd);
  }

  void run() {
    std::vector<pollfd> pfds;
    while (!stop.load()) {
      pfds.clear();
      pfds.push_back({listen_fd, POLLIN, 0});
      pfds.push_back({wake_r, POLLIN, 0});
      Clock::time_point nearest = Clock::time_point::max();
      for (auto& [fd, c] : conns) {
        short ev = POLLIN;
        if (!c->outbuf.empty()) ev |= POLLOUT;
        pfds.push_back({fd, ev, 0});
        if (c->waiting && c->wait_deadline < nearest)
          nearest = c->wait_deadline;
      }
      int timeout_ms = 500;
      if (nearest != Clock::time_point::max()) {
        auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                      nearest - Clock::now())
                      .count();
        timeout_ms = static_cast<int>(std::max<long long>(
            0, std::min<long long>(ms, 500)));
      }
      int rc = poll(pfds.data(), pfds.size(), timeout_ms);
      if (stop.load()) break;
      // expire parked WAITs even when poll timed out
      for (auto& [fd, c] : conns)
        if (c->waiting) try_serve_wait(c.get());
      if (rc <= 0) continue;

      if (pfds[0].revents & POLLIN) {
        for (;;) {
          int cfd = accept(listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          auto conn = std::make_unique<Conn>();
          conn->fd = cfd;
          conns.emplace(cfd, std::move(conn));
        }
      }
      if (pfds[1].revents & POLLIN) {
        char buf[64];
        while (read(wake_r, buf, sizeof(buf)) > 0) {
        }
      }
      for (size_t i = 2; i < pfds.size(); ++i) {
        int fd = pfds[i].fd;
        auto it = conns.find(fd);
        if (it == conns.end()) continue;
        Conn* c = it->second.get();
        if (pfds[i].revents & (POLLERR | POLLHUP | POLLNVAL)) {
          drop(fd);
          continue;
        }
        if (pfds[i].revents & POLLIN) {
          char buf[4096];
          bool closed = false;
          for (;;) {
            ssize_t n = read(fd, buf, sizeof(buf));
            if (n > 0) {
              c->inbuf.append(buf, static_cast<size_t>(n));
            } else if (n == 0) {
              closed = true;
              break;
            } else {
              break;  // EAGAIN
            }
          }
          size_t pos;
          while ((pos = c->inbuf.find('\n')) != std::string::npos) {
            std::string line = c->inbuf.substr(0, pos);
            if (!line.empty() && line.back() == '\r') line.pop_back();
            c->inbuf.erase(0, pos + 1);
            if (!line.empty()) handle_line(c, line);
          }
          if (closed) {
            drop(fd);
            continue;
          }
        }
        if (!c->outbuf.empty()) {
          ssize_t n = write(fd, c->outbuf.data(), c->outbuf.size());
          if (n > 0) c->outbuf.erase(0, static_cast<size_t>(n));
          else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) drop(fd);
        }
      }
    }
  }
};

std::mutex g_mu;
std::unordered_map<int, std::unique_ptr<Server>> g_servers;
int g_next_id = 1;

}  // namespace

extern "C" {

// Start a store daemon; returns handle id >= 0 (or -1). *out_port gets the
// bound port (useful with port=0).
int pt_store_start(const char* host, int port, int* out_port) {
  auto srv = std::make_unique<Server>();
  if (!srv->listen_on(host, port)) return -1;
  if (out_port) *out_port = srv->port;
  srv->loop = std::thread([s = srv.get()] { s->run(); });
  std::lock_guard<std::mutex> lk(g_mu);
  int id = g_next_id++;
  g_servers.emplace(id, std::move(srv));
  return id;
}

int pt_store_port(int id) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_servers.find(id);
  return it == g_servers.end() ? -1 : it->second->port;
}

void pt_store_stop(int id) {
  std::unique_ptr<Server> srv;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_servers.find(id);
    if (it == g_servers.end()) return;
    srv = std::move(it->second);
    g_servers.erase(it);
  }
  srv->shutdown();
}

}  // extern "C"
