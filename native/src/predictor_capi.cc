// C inference ABI over paddle_tpu exported artifacts.
//
// Reference capability: paddle/fluid/inference/capi_exp/pd_inference_api.h —
// a plain-C predictor surface (create/run/get-output) so non-C++/Python
// serving stacks (Go, Rust, Java via FFI) can execute exported models.
//
// TPU-native design: the artifact is a serialized StableHLO module
// (jit.save/.pdmodel) whose execution engine IS the XLA runtime that jax
// hosts. Rather than reimplementing a PJRT host in C++, this library embeds
// CPython and drives paddle_tpu.inference.Predictor through the CPython C
// API (the image has no pybind11 — plain Python.h). The C caller never sees
// Python; the ABI below is self-contained and mirrors the capi_exp naming.
//
// Build (see io/native.py build_infer_capi):
//   g++ -O2 -std=c++17 -shared -fPIC predictor_capi.cc \
//       $(python3-config --includes) -lpython3.X -o libptinfer.so
//
// Threading: all entry points serialize on the GIL; one interpreter is
// initialized lazily on first PD_ConfigCreate and kept for process life.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

extern "C" {

struct PD_Config {
  std::string prog_file;
  std::string params_file;
};

struct PD_Predictor {
  PyObject* predictor;  // paddle_tpu.inference.Predictor
};

struct PD_Tensor {
  PyObject* handle;     // paddle_tpu.inference.Tensor (named handle)
};

static void ensure_python() {
  // once_flag: concurrent first calls from different server threads must
  // not race Py_IsInitialized/Py_InitializeEx (concurrent init is UB).
  static std::once_flag flag;
  std::call_once(flag, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Py_InitializeEx leaves the calling thread holding the GIL. Release
      // it here so that PD_* entry points — which each take the GIL via
      // PyGILState_Ensure/Release — can be called from ANY thread of a
      // multithreaded serving stack without deadlocking on the initializer
      // thread's never-released GIL.
      PyEval_SaveThread();
    }
  });
}

// ---------------------------------------------------------------- Config
PD_Config* PD_ConfigCreate() {
  ensure_python();
  return new PD_Config();
}

void PD_ConfigSetModel(PD_Config* c, const char* prog_file,
                       const char* params_file) {
  c->prog_file = prog_file ? prog_file : "";
  c->params_file = params_file ? params_file : "";
}

void PD_ConfigDestroy(PD_Config* c) { delete c; }

// ------------------------------------------------------------- Predictor
PD_Predictor* PD_PredictorCreate(PD_Config* c) {
  ensure_python();
  PyGILState_STATE gil = PyGILState_Ensure();
  PD_Predictor* out = nullptr;

  PyObject* mod = PyImport_ImportModule("paddle_tpu.inference");
  if (mod) {
    PyObject* cfg_cls = PyObject_GetAttrString(mod, "Config");
    PyObject* cfg = cfg_cls ? PyObject_CallFunction(
        cfg_cls, "ss", c->prog_file.c_str(), c->params_file.c_str()) : nullptr;
    PyObject* create = cfg ? PyObject_GetAttrString(mod, "create_predictor")
                           : nullptr;
    PyObject* pred = create ? PyObject_CallFunctionObjArgs(create, cfg, nullptr)
                            : nullptr;
    if (pred) {
      out = new PD_Predictor{pred};
    }
    Py_XDECREF(create);
    Py_XDECREF(cfg);
    Py_XDECREF(cfg_cls);
    Py_DECREF(mod);
  }
  if (!out && PyErr_Occurred()) PyErr_Print();  // PyErr_Print clears
  PyGILState_Release(gil);
  return out;
}

void PD_PredictorDestroy(PD_Predictor* p) {
  if (!p) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(p->predictor);
  PyGILState_Release(gil);
  delete p;
}

static char* dup_pystr(PyObject* s) {
  const char* c = PyUnicode_AsUTF8(s);
  return strdup(c ? c : "");
}

size_t PD_PredictorGetInputNum(PD_Predictor* p) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* names = PyObject_CallMethod(p->predictor, "get_input_names", NULL);
  size_t n = names ? static_cast<size_t>(PyList_Size(names)) : 0;
  Py_XDECREF(names);
  PyGILState_Release(gil);
  return n;
}

// caller frees with free()
char* PD_PredictorGetInputName(PD_Predictor* p, size_t idx) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* names = PyObject_CallMethod(p->predictor, "get_input_names", NULL);
  char* out = nullptr;
  if (names && idx < static_cast<size_t>(PyList_Size(names))) {
    out = dup_pystr(PyList_GetItem(names, static_cast<Py_ssize_t>(idx)));
  }
  Py_XDECREF(names);
  PyGILState_Release(gil);
  return out;
}

size_t PD_PredictorGetOutputNum(PD_Predictor* p) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* names = PyObject_CallMethod(p->predictor, "get_output_names", NULL);
  size_t n = names ? static_cast<size_t>(PyList_Size(names)) : 0;
  Py_XDECREF(names);
  PyGILState_Release(gil);
  return n;
}

char* PD_PredictorGetOutputName(PD_Predictor* p, size_t idx) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* names = PyObject_CallMethod(p->predictor, "get_output_names", NULL);
  char* out = nullptr;
  if (names && idx < static_cast<size_t>(PyList_Size(names))) {
    out = dup_pystr(PyList_GetItem(names, static_cast<Py_ssize_t>(idx)));
  }
  Py_XDECREF(names);
  PyGILState_Release(gil);
  return out;
}

PD_Tensor* PD_PredictorGetInputHandle(PD_Predictor* p, const char* name) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* h = PyObject_CallMethod(p->predictor, "get_input_handle", "s", name);
  if (!h) PyErr_Print();  // diagnostic to stderr; also clears the error
  PyGILState_Release(gil);
  if (!h) return nullptr;
  return new PD_Tensor{h};
}

PD_Tensor* PD_PredictorGetOutputHandle(PD_Predictor* p, const char* name) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* h = PyObject_CallMethod(p->predictor, "get_output_handle", "s", name);
  if (!h) PyErr_Print();
  PyGILState_Release(gil);
  if (!h) return nullptr;
  return new PD_Tensor{h};
}

int PD_PredictorRun(PD_Predictor* p) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* r = PyObject_CallMethod(p->predictor, "run", NULL);
  int ok = r != nullptr;
  if (!ok && PyErr_Occurred()) PyErr_Print();
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return ok;
}

// ---------------------------------------------------------------- Tensor
void PD_TensorDestroy(PD_Tensor* t) {
  if (!t) return;
  PyGILState_STATE gil = PyGILState_Ensure();
  Py_XDECREF(t->handle);
  PyGILState_Release(gil);
  delete t;
}

void PD_TensorReshape(PD_Tensor* t, size_t ndim, const int32_t* shape) {
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* lst = PyList_New(static_cast<Py_ssize_t>(ndim));
  for (size_t i = 0; i < ndim; ++i) {
    PyList_SetItem(lst, static_cast<Py_ssize_t>(i),
                   PyLong_FromLong(shape[i]));
  }
  PyObject* r = PyObject_CallMethod(t->handle, "reshape", "O", lst);
  Py_XDECREF(r);
  Py_DECREF(lst);
  PyGILState_Release(gil);
}

size_t PD_TensorGetNumel(PD_Tensor* t) {
  PyGILState_STATE gil = PyGILState_Ensure();
  size_t n = 1;
  PyObject* shape = PyObject_CallMethod(t->handle, "shape", NULL);
  if (shape) {
    Py_ssize_t nd = PySequence_Size(shape);
    for (Py_ssize_t i = 0; i < nd; ++i) {
      PyObject* d = PySequence_GetItem(shape, i);
      n *= static_cast<size_t>(PyLong_AsLong(d));
      Py_XDECREF(d);
    }
    Py_DECREF(shape);
  }
  PyGILState_Release(gil);
  return n;
}

size_t PD_TensorGetShape(PD_Tensor* t, int32_t* shape_out, size_t max_ndim) {
  PyGILState_STATE gil = PyGILState_Ensure();
  size_t nd_out = 0;
  PyObject* shape = PyObject_CallMethod(t->handle, "shape", NULL);
  if (shape) {
    Py_ssize_t nd = PySequence_Size(shape);
    nd_out = static_cast<size_t>(nd);
    for (Py_ssize_t i = 0; i < nd && static_cast<size_t>(i) < max_ndim; ++i) {
      PyObject* d = PySequence_GetItem(shape, i);
      shape_out[i] = static_cast<int32_t>(PyLong_AsLong(d));
      Py_XDECREF(d);
    }
    Py_DECREF(shape);
  }
  PyGILState_Release(gil);
  return nd_out;
}

// dtype codes follow capi_exp PD_DataType: 0=float32, 1=int64, 2=int32
static const char* dtype_name(int dtype) {
  switch (dtype) {
    case 1: return "int64";
    case 2: return "int32";
    default: return "float32";
  }
}

static int dtype_size(int dtype) { return dtype == 0 || dtype == 2 ? 4 : 8; }

int PD_TensorCopyFromCpu(PD_Tensor* t, const void* data, int dtype) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int ok = 0;
  size_t numel = 1;
  {
    PyObject* shape = PyObject_CallMethod(t->handle, "shape", NULL);
    if (shape) {
      Py_ssize_t nd = PySequence_Size(shape);
      for (Py_ssize_t i = 0; i < nd; ++i) {
        PyObject* d = PySequence_GetItem(shape, i);
        numel *= static_cast<size_t>(PyLong_AsLong(d));
        Py_XDECREF(d);
      }
      Py_DECREF(shape);
    }
  }
  // np.frombuffer(bytes, dtype).reshape(handle.shape) -> copy_from_cpu
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* bytes = PyBytes_FromStringAndSize(
      static_cast<const char*>(data),
      static_cast<Py_ssize_t>(numel * dtype_size(dtype)));
  if (np && bytes) {
    PyObject* arr = PyObject_CallMethod(np, "frombuffer", "Os", bytes,
                                        dtype_name(dtype));
    PyObject* shape = arr ? PyObject_CallMethod(t->handle, "shape", NULL) : nullptr;
    PyObject* shaped = shape ? PyObject_CallMethod(arr, "reshape", "O", shape)
                             : nullptr;
    if (shaped) {
      PyObject* r = PyObject_CallMethod(t->handle, "copy_from_cpu", "O", shaped);
      ok = r != nullptr;
      Py_XDECREF(r);
    }
    Py_XDECREF(shaped);
    Py_XDECREF(shape);
    Py_XDECREF(arr);
  }
  if (!ok && PyErr_Occurred()) PyErr_Print();
  Py_XDECREF(bytes);
  Py_XDECREF(np);
  PyGILState_Release(gil);
  return ok;
}

int PD_TensorCopyToCpu(PD_Tensor* t, void* data, size_t nbytes) {
  PyGILState_STATE gil = PyGILState_Ensure();
  int ok = 0;
  PyObject* arr = PyObject_CallMethod(t->handle, "copy_to_cpu", NULL);
  if (arr) {
    PyObject* contig = PyObject_CallMethod(arr, "tobytes", NULL);
    if (contig) {
      Py_ssize_t n = PyBytes_Size(contig);
      if (static_cast<size_t>(n) <= nbytes) {
        memcpy(data, PyBytes_AsString(contig), static_cast<size_t>(n));
        ok = 1;
      }
      Py_DECREF(contig);
    }
    Py_DECREF(arr);
  }
  if (!ok && PyErr_Occurred()) PyErr_Print();
  PyGILState_Release(gil);
  return ok;
}

}  // extern "C"
