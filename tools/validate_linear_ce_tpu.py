"""Hardware validation + A/B timing for the fused linear-CE kernel.

Run on the axon chip:
    PYTHONPATH=/root/repo:$PYTHONPATH python tools/validate_linear_ce_tpu.py

Checks (flagship shape T=6144 H=2048 V=50304 bf16):
  1. forward loss parity Pallas vs legacy chunked-XLA path
  2. dx/dW parity (bf16 tolerances)
  3. fwd+bwd wall time of both paths via a fused multi-step scan with a
     host-read fence (bench.py protocol — per memory, naive timing lies)
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.ops.pallas.linear_ce import linear_cross_entropy  # noqa: E402


def legacy_ce(x2d, w, labels, chunk=512):
    t, h = x2d.shape
    nc = t // chunk
    xs = x2d.reshape(nc, chunk, h)
    ls = labels.reshape(nc, chunk)

    def chunk_loss(args):
        xc, lc = args
        def inner(xc, lc):
            logits = jnp.einsum("ch,vh->cv", xc, w).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
            return lse - gold
        return jax.checkpoint(inner)(xc, lc)

    return lax.map(chunk_loss, (xs, ls)).reshape(t)


def main():
    T, H, V = 6144, 2048, 50304
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, H).astype(np.float32) * 0.5, jnp.bfloat16)
    w = jnp.asarray(rng.randn(V, H).astype(np.float32) * 0.05, jnp.bfloat16)
    labels = jnp.asarray(rng.randint(0, V, T).astype(np.int32))
    coef = jnp.asarray(rng.rand(T).astype(np.float32))

    def loss_pallas(x, w):
        return jnp.sum(coef * linear_cross_entropy(x, w, labels))

    def loss_legacy(x, w):
        return jnp.sum(coef * legacy_ce(x, w, labels))

    # 1. forward parity
    fp = jax.jit(loss_pallas)(x, w)
    fl = jax.jit(loss_legacy)(x, w)
    print("fwd pallas", float(fp), "legacy", float(fl),
          "rel", abs(float(fp) - float(fl)) / abs(float(fl)))

    # 2. grad parity
    gp = jax.jit(jax.grad(loss_pallas, argnums=(0, 1)))(x, w)
    gl = jax.jit(jax.grad(loss_legacy, argnums=(0, 1)))(x, w)
    for name, a, b in (("dx", gp[0], gl[0]), ("dW", gp[1], gl[1])):
        a = np.asarray(a, np.float32); b = np.asarray(b, np.float32)
        denom = np.abs(b).max() + 1e-9
        print(f"{name} max-abs-diff {np.abs(a - b).max():.4e} "
              f"(rel-to-max {np.abs(a - b).max() / denom:.4e})")

    # 3. timed fwd+bwd scan (N steps fused into one launch)
    N = 20

    def make_step(fn):
        g = jax.grad(fn, argnums=(0, 1))
        def body(carry, _):
            xx, acc = carry
            dx, dw = g(xx, w)
            # fold grads back in so steps are data-dependent (no DCE)
            return (xx + 0.0 * dx, acc + jnp.float32(jnp.sum(dw[0, :1]))), None
        def run(xx):
            (xo, acc), _ = lax.scan(body, (xx, jnp.float32(0)), None, length=N)
            return acc + jnp.sum(xo[:1, :1].astype(jnp.float32))
        return jax.jit(run)

    for name, fn in (("pallas", loss_pallas), ("legacy", loss_legacy)):
        run = make_step(fn)
        _ = float(run(x))  # warm compile
        best = float("inf")
        for _rep in range(3):
            t0 = time.perf_counter()
            _ = float(run(x))
            best = min(best, time.perf_counter() - t0)
        print(f"{name}: {best / N * 1e3:.2f} ms/step (fwd+bwd, N={N})")


if __name__ == "__main__":
    main()
