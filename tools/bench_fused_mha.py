"""Microbench the fused short-seq MHA kernel vs the XLA reference path.

Protocol per memory/bench-chip-reality: N calls fused into ONE lax.scan
executable, 1-element host read as fence, best of 3 launches.

Usage: python tools/bench_fused_mha.py [vit|bert]
"""
import sys
import time

sys.path.insert(0, ".")

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_mha import fused_mha, mha_reference_packed

SHAPES = {
    "vit": (32, 197, 16, 64, 0.0),
    "bert": (32, 512, 12, 64, 0.1),
}


def timed(fn, qkv, iters=50):
    """One scan over `iters` applications; returns ms per application."""

    def body(c, _):
        o = fn(c)
        # feed a hash of the output back so scan can't be elided
        return c + 0.0 * jnp.mean(o), ()

    @jax.jit
    def run(a):
        out, _ = jax.lax.scan(body, a, None, length=iters)
        return jnp.mean(out)

    _ = float(run(qkv))  # compile + warm
    best = float("inf")
    for _rep in range(3):
        t0 = time.perf_counter()
        _ = float(run(qkv))
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1e3


def timed_grad(fn, qkv, iters=50):
    def loss(a):
        return jnp.sum(fn(a) ** 2)

    def body(c, _):
        g = jax.grad(loss)(c)
        return c + 0.0 * jnp.mean(g), ()

    @jax.jit
    def run(a):
        out, _ = jax.lax.scan(body, a, None, length=iters)
        return jnp.mean(out)

    _ = float(run(qkv))
    best = float("inf")
    for _rep in range(3):
        t0 = time.perf_counter()
        _ = float(run(qkv))
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1e3


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "vit"
    b, s, nh, hd, drop = SHAPES[which]
    rng = np.random.RandomState(0)
    qkv = jnp.asarray(rng.randn(b, s, 3 * nh * hd).astype(np.float32)
                      ).astype(jnp.bfloat16) * 0.3
    print(f"shape B={b} S={s} nh={nh} hd={hd} bf16")

    ms = timed(lambda a: mha_reference_packed(a, nh, score_dtype=a.dtype),
               qkv)
    print(f"xla reference (bf16 scores)   fwd: {ms:8.3f} ms")
    ms = timed_grad(lambda a: mha_reference_packed(a, nh,
                                                   score_dtype=a.dtype), qkv)
    print(f"xla reference (bf16 scores) f+bwd: {ms:8.3f} ms")

    for G in (nh, nh // 2, nh // 4):
        if G < 1 or nh % G:
            continue
        ms = timed(lambda a: fused_mha(a, nh, heads_per_program=G), qkv)
        print(f"fused_mha G={G:<3d}               fwd: {ms:8.3f} ms")
        ms = timed_grad(lambda a: fused_mha(a, nh, heads_per_program=G), qkv)
        print(f"fused_mha G={G:<3d}             f+bwd: {ms:8.3f} ms")

    if drop > 0:
        ms = timed_grad(lambda a: fused_mha(a, nh, dropout_p=drop,
                                            dropout_seed=3.0), qkv)
        print(f"fused_mha dropout={drop}      f+bwd: {ms:8.3f} ms")


if __name__ == "__main__":
    main()
