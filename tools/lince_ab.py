"""Split A/B: fwd-only and fwd+bwd times for pallas vs legacy linear-CE.

Timing traps handled: per-step input varies via a runtime scale vector (no
loop-invariant hoisting), and outputs are consumed via sum-of-squares (no
slice-narrowing through the matmuls). bench.py protocol otherwise: one
fused scan launch, host-read fence, best of 3.
"""
import os, sys, time
import numpy as np
import jax, jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from paddle_tpu.ops.pallas.linear_ce import linear_cross_entropy  # noqa
from tools.validate_linear_ce_tpu import legacy_ce  # noqa

T, H, V = (int(os.environ.get(k, d)) for k, d in
           (("T", 6144), ("H", 2048), ("V", 50304)))
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(T, H).astype(np.float32) * 0.5, jnp.bfloat16)
w = jnp.asarray(rng.randn(V, H).astype(np.float32) * 0.05, jnp.bfloat16)
labels = jnp.asarray(rng.randint(0, V, T).astype(np.int32))
coef = jnp.asarray(rng.rand(T).astype(np.float32))

cfg = dict(block_t=int(os.environ.get("BT", "512")),
           block_v=int(os.environ.get("BV", "384")),
           bwd_chunks=int(os.environ.get("BC", "4")))
print("cfg", cfg, "T,H,V", (T, H, V))

def loss_pallas(xx, ww):
    return jnp.sum(coef * linear_cross_entropy(xx, ww, labels, **cfg))

def loss_legacy(xx, ww):
    return jnp.sum(coef * legacy_ce(xx, ww, labels))

N = 30
ps = jnp.ones((N,), jnp.bfloat16)   # runtime values; compiler can't fold

def timeit(per_step):
    def body(acc, p):
        return acc + per_step(x * p), None
    def run(ps):
        acc, _ = lax.scan(body, jnp.float32(0), ps)
        return acc
    run = jax.jit(run)
    _ = float(run(ps))
    best = float("inf")
    for _r in range(3):
        t0 = time.perf_counter()
        _ = float(run(ps))
        best = min(best, time.perf_counter() - t0)
    return best / N * 1e3

only = os.environ.get("ONLY")
pairs = [p for p in (("pallas", loss_pallas), ("legacy", loss_legacy))
         if not only or p[0] == only]
for name, fn in pairs:
    f = 0.0 if os.environ.get("SKIP_FWD") else timeit(
        lambda xx, fn=fn: fn(xx, w))
    g = jax.grad(fn, argnums=(0, 1))
    def full(xx, g=g):
        dx, dw = g(xx, w)
        return (jnp.sum(dx.astype(jnp.float32) ** 2)
                + jnp.sum(dw.astype(jnp.float32) ** 2))
    fb = timeit(full)
    print(f"{name}: fwd {f:.2f} ms   fwd+bwd(+consume) {fb:.2f} ms")
