"""Op micro-benchmark suite + regression gate.

Reference capability: tools/ci_op_benchmark.sh + check_op_benchmark_result.py
— CI runs op benchmarks against the develop wheel and fails on relative
regressions. TPU-native analog: this file measures a curated set of op
kernels (the hot families: matmul, attention, norm, elementwise, reduction,
gather/scatter, CE) and writes JSON; `--check BASELINE.json` compares the
current run against a saved baseline and fails (exit 1) if any op regresses
beyond the tolerance — the same relative-gate contract.

Usage:
    python tools/op_bench.py --out op_bench.json          # record
    python tools/op_bench.py --check op_bench.json        # gate (±25%)
    python tools/op_bench.py --check op_bench.json --tol 0.10

Runs on whatever backend jax selects (TPU via axon, else CPU); baselines
are only comparable within one backend/host (store them per-machine, like
the reference's per-CI-pool baselines).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _cases():
    import jax
    import jax.numpy as jnp
    import numpy as np

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    F = 1 if on_tpu else 4  # shrink on CPU so the gate stays fast
    B, S, H = 8 // F or 1, 1024 // F, 2048 // F
    rng = np.random.RandomState(0)

    def f32(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32))

    def bf16(*shape):
        return f32(*shape).astype(jnp.bfloat16)

    x = bf16(B * S, H)
    w = bf16(H, 4 * H)
    ids = jnp.asarray(rng.randint(0, 50304, (B, S)).astype(np.int32))
    emb = bf16(50304, H)
    q = bf16(B, S, 16, H // 16)
    lnw, lnb = f32(H), f32(H)

    from paddle_tpu.ops.attention import attention_reference

    cases = {
        "matmul_bf16": (lambda: x @ w, ()),
        "elementwise_gelu": (lambda: jax.nn.gelu(x), ()),
        "reduce_mean_axis0": (lambda: x.astype(jnp.float32).mean(0), ()),
        "layer_norm": (lambda: _ln(x, lnw, lnb), ()),
        "embedding_gather": (lambda: jnp.take(emb, ids, axis=0), ()),
        "attention_sdpa": (lambda: attention_reference(q, q, q,
                                                       is_causal=True), ()),
        "softmax_ce": (lambda: _ce(x[: B * S // 4], ids.reshape(-1)[: B * S // 4]), ()),
        "cumsum": (lambda: jnp.cumsum(x, axis=1), ()),
        "sort": (lambda: jnp.sort(x[:256], axis=1), ()),
        "scatter_add": (lambda: jnp.zeros((50304, H), jnp.float32)
                        .at[ids.reshape(-1)].add(x.astype(jnp.float32)[: B * S]), ()),
    }

    def _ln(a, wg, bg):
        a32 = a.astype(jnp.float32)
        mu = a32.mean(-1, keepdims=True)
        var = a32.var(-1, keepdims=True)
        return ((a32 - mu) * jax.lax.rsqrt(var + 1e-5) * wg + bg).astype(a.dtype)

    def _ce(logit_in, labels):
        logits = (logit_in @ w[:, :H]).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, (labels[: logits.shape[0]] % H)[:, None], axis=-1)[..., 0]
        return (lse - gold).mean()

    return cases


def run(iters=20):
    import jax
    results = {}
    for name, (fn, _) in _cases().items():
        jitted = jax.jit(fn)
        out = jitted()
        jax.block_until_ready(out)       # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jitted()
        jax.block_until_ready(out)
        results[name] = (time.perf_counter() - t0) / iters * 1e6  # us
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None, help="write baseline JSON")
    ap.add_argument("--check", default=None, help="baseline JSON to gate against")
    ap.add_argument("--tol", type=float, default=0.25,
                    help="max allowed relative slowdown (0.25 = +25%%)")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args()

    res = run(args.iters)
    for k, v in sorted(res.items()):
        print(f"{v:10.1f} us  {k}")

    if args.out:
        with open(args.out, "w") as f:
            json.dump(res, f, indent=1)
        print(f"baseline written: {args.out}")
    if args.check:
        with open(args.check) as f:
            base = json.load(f)
        bad = []
        for k, us in res.items():
            if k in base and us > base[k] * (1 + args.tol):
                bad.append((k, base[k], us))
        if bad:
            for k, b, c in bad:
                print(f"REGRESSION {k}: {b:.1f}us -> {c:.1f}us "
                      f"(+{(c / b - 1) * 100:.0f}%)", file=sys.stderr)
            sys.exit(1)
        print(f"op benchmark gate OK ({len(res)} ops within "
              f"+{args.tol * 100:.0f}% of baseline)")


if __name__ == "__main__":
    main()
