#!/usr/bin/env python
"""Flight-recorder smoke (ISSUE 17) — the tier-1 gate for anomaly-
triggered profiling, end-to-end on a live toy engine:

  1. a ServingEngine decodes closed-loop with a FlightRecorder attached
     via serve_telemetry(flightrec=...) while a scraper thread hits
     /profilez concurrently — zero post-warmup jit cache misses (the
     r15 scrape invariant extends to profiling: the recorder only flips
     host-side state at step boundaries);
  2. an INJECTED SLO breach (unmeetable e2e/ttft targets over real
     traffic) fires burn-rate alerts on the trigger bus -> exactly ONE
     trigger-pinned capture (the multi-target alert storm coalesces),
     discoverable via /profilez, whose KernelView table is byte-
     identical to what trace_analysis renders from the same trace file,
     and whose raw trace.json.gz downloads intact;
  3. /tracez?fmt=chrome renders the retained request span trees as
     loadable trace-event JSON;
  4. tools/perf_diff.py gates the checked-in mini_step fixture against
     itself at 0%% (exit 0) and catches a planted 2x kernel slowdown
     (names the kernel, exit 1).

The capture backend is the mini_step fixture (a CPU jax capture has no
device lanes — the analysis path is what this smoke pins; the real
JaxProfilerBackend is exercised for liveness by unit tests).

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/flightrec_smoke.py [--batches 6] [--json]
"""
from __future__ import annotations

import argparse
import gzip
import json
import os
import subprocess
import sys
import tempfile
import threading

import jax

jax.config.update("jax_platforms", "cpu")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

import numpy as np  # noqa: E402

FIXTURE = os.path.join(ROOT, "tests", "fixtures",
                       "mini_step.trace.json.gz")


class ProfilezScraper(threading.Thread):
    """Hammer /profilez (list mode) while decode runs."""

    def __init__(self, srv, interval: float = 0.05):
        super().__init__(name="flightrec-smoke-scraper", daemon=True)
        self.srv = srv
        self.interval = interval
        self.stop = threading.Event()
        self.scrapes = 0
        self.errors = []

    def run(self):
        from urllib.request import urlopen
        while not self.stop.is_set():
            try:
                p = json.loads(urlopen(self.srv.url("/profilez"),
                                       timeout=5).read())
                if "summary" not in p or "captures" not in p:
                    raise AssertionError("/profilez missing keys")
                self.scrapes += 1
            except Exception as e:          # noqa: BLE001 — the gate
                self.errors.append(f"{type(e).__name__}: {e}")
                return
            if self.stop.wait(timeout=self.interval):
                return


def run_block(engine, prompts, batches):
    B = engine.config.max_batch
    for b in range(batches):
        for i in range(B):
            engine.submit(prompts[(b * B + i) % len(prompts)])
        engine.drain()


def perf_diff_legs(failures):
    """Leg 4: the CLI gate on the checked-in fixture."""
    base = [sys.executable, os.path.join(ROOT, "tools", "perf_diff.py")]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(base + [FIXTURE, FIXTURE, "--steps", "1",
                               "--regress-pct", "0"],
                       capture_output=True, text=True, env=env)
    if r.returncode != 0:
        failures.append(f"perf_diff self-diff exited {r.returncode} "
                        f"(want 0): {r.stderr.strip()[:200]}")
    if "+0.0%" not in r.stdout and "0.000" not in r.stdout:
        failures.append("perf_diff self-diff did not report 0% deltas")

    with gzip.open(FIXTURE, "rt") as f:
        data = json.load(f)
    slowed = None
    for e in data["traceEvents"]:
        if e.get("ph") == "X" and e.get("name") == "fusion.1":
            e["dur"] = e["dur"] * 2
            slowed = e["name"]
    doctored = os.path.join(tempfile.mkdtemp(prefix="flightrec-smoke-"),
                            "doctored.trace.json.gz")
    with gzip.open(doctored, "wt") as f:
        json.dump(data, f)
    r = subprocess.run(base + [FIXTURE, doctored, "--steps", "1",
                               "--regress-pct", "5"],
                       capture_output=True, text=True, env=env)
    if r.returncode != 1:
        failures.append(f"perf_diff vs 2x-doctored trace exited "
                        f"{r.returncode} (want 1)")
    if slowed not in r.stderr:
        failures.append(f"perf_diff did not name the slowed kernel "
                        f"{slowed!r}: {r.stderr.strip()[:200]}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batches", type=int, default=6,
                    help="micro-batches per traffic block")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.obs import FixtureBackend, FlightRecorder, SLOMonitor
    from paddle_tpu.profiler.trace_analysis import analyze

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (int(rng.randint(3, 13)),)).astype(np.int64)
               for _ in range(16)]
    for p in prompts[:2]:                   # warmup executable set
        engine.submit(p)
    engine.drain()

    failures = []
    workdir = tempfile.mkdtemp(prefix="flightrec-smoke-")
    rec = FlightRecorder(
        os.path.join(workdir, "captures"), ring=4, every=0,
        trigger_steps=2, cooldown_s=600.0,
        backend=FixtureBackend(FIXTURE),
        jsonl_path=os.path.join(workdir, "rows.jsonl"))
    # two unmeetable targets: real traffic breaches BOTH -> an alert
    # storm on the trigger bus that must still yield ONE capture
    slo = SLOMonitor("e2e_p99=1ms,ttft_p99=1ms", engine.metrics,
                     long_s=60.0, short_s=5.0, burn_threshold=1.0)
    miss0 = compile_cache_misses()
    srv = engine.serve_telemetry(slo=slo, flightrec=rec)
    scraper = ProfilezScraper(srv)
    scraper.start()
    try:
        slo.poll()                          # baseline snapshot
        run_block(engine, prompts, args.batches)
        slo.poll()                          # breach -> alert transition
        if not slo.breaching:
            failures.append("injected SLO breach did not register "
                            "(targets should be unmeetable)")
        run_block(engine, prompts, args.batches)  # capture these steps
        slo.poll()
    finally:
        scraper.stop.set()
        scraper.join(timeout=5)
    if scraper.errors:
        failures.append(f"/profilez scrape failed concurrently with "
                        f"decode: {scraper.errors[0]}")
    if scraper.scrapes < 1:
        failures.append("scraper completed zero /profilez passes")

    dm = compile_cache_misses() - miss0
    if dm:
        failures.append(f"{dm} jit cache misses post-warmup with the "
                        f"flight recorder attached (must be 0)")

    # exactly ONE trigger-pinned capture from the alert storm
    s = rec.summary()
    pinned = [c for c in rec.profilez({})["captures"] if c["pinned"]]
    if s["captures_total"] != 1 or len(pinned) != 1:
        failures.append(f"want exactly 1 pinned capture, got "
                        f"{s['captures_total']} total / {len(pinned)} "
                        f"pinned (triggers={s['triggers_total']}, "
                        f"coalesced={s['triggers_coalesced']}, "
                        f"suppressed={s['triggers_suppressed']})")
    if s["triggers_total"] < 2:
        failures.append(f"expected an alert storm (>=2 triggers), got "
                        f"{s['triggers_total']}")

    kernel_match = False
    if pinned:
        cap = pinned[0]
        if not any(t["kind"] == "slo_alert" for t in cap["triggers"]):
            failures.append(f"pinned capture's triggers carry no "
                            f"slo_alert: {cap['triggers']}")
        from urllib.request import urlopen
        listing = json.loads(urlopen(srv.url("/profilez"),
                                     timeout=5).read())
        if not any(c["id"] == cap["id"] and c["pinned"]
                   for c in listing["captures"]):
            failures.append("pinned capture not discoverable via "
                            "/profilez")
        view = json.loads(urlopen(
            srv.url(f"/profilez?id={cap['id']}&view=kernel"),
            timeout=5).read())
        local = analyze(cap["trace_path"], steps=cap["steps"])
        if view.get("table") == local.kernel_view():
            kernel_match = True
        else:
            failures.append("/profilez KernelView differs from "
                            "trace_analysis on the same trace file")
        raw = urlopen(srv.url(f"/profilez?id={cap['id']}&fmt=raw"),
                      timeout=5).read()
        with open(cap["trace_path"], "rb") as f:
            if raw != f.read():
                failures.append("raw trace download differs from the "
                                "capture's file")
        rows = [json.loads(line) for line in
                open(os.path.join(workdir, "rows.jsonl"))]
        cap_rows = [r for r in rows if "capture" in r]
        if len(cap_rows) != 1 or not any(
                t.get("row", {}).get("slo_alert") is not None
                for t in cap_rows[0]["capture"]["triggers"]):
            failures.append("capture JSONL row missing or not linked "
                            "to the alert's own row")

    # chrome-trace export of the request timeline
    from urllib.request import urlopen
    chrome = json.loads(urlopen(srv.url("/tracez?fmt=chrome&limit=8"),
                                timeout=5).read())
    evs = chrome.get("traceEvents", [])
    if not any(e.get("ph") == "X" and e.get("name") == "request"
               for e in evs):
        failures.append("/tracez?fmt=chrome carries no request slices")

    srv.close()
    perf_diff_legs(failures)

    out = {"profilez_scrapes": scraper.scrapes,
           "post_warmup_jit_misses": dm,
           "slo_alerts": slo.alerts_total,
           "triggers": s["triggers_total"],
           "coalesced": s["triggers_coalesced"],
           "suppressed": s["triggers_suppressed"],
           "captures_total": s["captures_total"],
           "pinned": len(pinned),
           "kernelview_matches": kernel_match,
           "chrome_events": len(evs),
           "ok": not failures, "failures": failures}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"flightrec_smoke: {out['slo_alerts']} SLO alerts -> "
              f"{out['triggers']} triggers -> {out['captures_total']} "
              f"capture(s) ({out['pinned']} pinned), "
              f"{out['profilez_scrapes']} concurrent /profilez passes, "
              f"jit misses {dm}")
        print(f"flightrec_smoke: KernelView match={kernel_match}, "
              f"chrome export {out['chrome_events']} events, "
              f"perf_diff gates exercised")
    for f in failures:
        print(f"flightrec_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("flightrec_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
