#!/usr/bin/env python
"""HBM-ledger smoke (ISSUE 18) — the tier-1 gate for memory
observability: boot a toy PAGED ServingEngine with a MemoryLedger
attached and prove the surface end-to-end:

  1. conservation: sum(device owner bytes) + unattributed ==
     `device.memory_allocated()` within tolerance, sampled repeatedly
     UNDER CHURN (admissions, frees, prefix sharing) — the ledger
     provably sums to the allocator's view;
  2. /memz (and the rest of the surface) answers CONCURRENTLY with live
     decode at ZERO post-warmup jit cache misses — a ledger read never
     syncs or compiles;
  3. OOM forensics: a chaos-injected allocation failure (AllocFailure at
     serving.step) produces a post-mortem artifact that names the
     largest owner and renders through tools/oom_report.py (subprocess,
     exit 0); the engine stays servable afterwards;
  4. mem-pressure episodes: forced pool oversubscription emits paired
     {"mem_pressure"} / {"mem_pressure_clear"} rows (one per episode).

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/memz_smoke.py [--batches 6] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class Scraper(threading.Thread):
    """GET + validate /memz, /metrics and /statusz in a loop — the
    concurrent-scrape leg of the zero-miss gate."""

    def __init__(self, srv, interval: float = 0.05):
        super().__init__(name="memz-smoke-scraper", daemon=True)
        self.srv = srv
        self.interval = interval
        self.stop = threading.Event()
        self.scrapes = 0
        self.errors = []

    def _one_pass(self):
        from urllib.request import urlopen
        from paddle_tpu.obs import lint_exposition
        m = json.loads(urlopen(self.srv.url("/memz?deltas=16"),
                               timeout=5).read())
        for key in ("owners", "attributed_bytes", "unattributed_bytes",
                    "deltas"):
            if key not in m:
                raise AssertionError(f"/memz missing {key}")
        if not any(o["owner"] == "kv_pool" for o in m["owners"]):
            raise AssertionError("/memz owners missing kv_pool")
        text = urlopen(self.srv.url("/metrics"), timeout=5).read().decode()
        lint_exposition(text)
        if "hbm_bytes" not in text or "hbm_headroom_bytes" not in text:
            raise AssertionError("/metrics missing hbm gauges")
        s = json.loads(urlopen(self.srv.url("/statusz"), timeout=5).read())
        if "memory" not in s or "kv_pool" not in s["memory"]["owners"]:
            raise AssertionError("/statusz missing memory block")

    def run(self):
        while not self.stop.is_set():
            try:
                self._one_pass()
                self.scrapes += 1
            except Exception as e:             # noqa: BLE001 — the gate
                self.errors.append(f"{type(e).__name__}: {e}")
                return
            if self.stop.wait(timeout=self.interval):
                return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batches", type=int, default=6,
                    help="churn micro-batches under the concurrent "
                         "scraper")
    ap.add_argument("--tolerance-frac", type=float, default=0.15,
                    help="|unattributed| bound as a fraction of the "
                         "allocator view (CPU live-array fallback "
                         "carries temporaries; allocator platforms sit "
                         "near 0)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.obs import MemoryLedger
    from paddle_tpu.resilience import AllocFailure, Injector

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4,
        paged=True, kv_block=4, kv_blocks=24, prefix_cache=True))
    # explicit capacity so the headroom gauge renders on the CPU host
    # (no allocator bytes_limit); generous enough to stay quiet
    ledger = engine.attach_memory_ledger(
        MemoryLedger(capacity_bytes=1 << 30))
    rng = np.random.RandomState(0)
    prefix = rng.randint(1, cfg.vocab_size, (8,)).astype(np.int64)
    prompts = []
    for i in range(16):
        if i % 2:          # shared-prefix half: exercises retain/COW
            suffix = rng.randint(1, cfg.vocab_size,
                                 (int(rng.randint(1, 5)),))
            prompts.append(np.concatenate([prefix, suffix])
                           .astype(np.int64))
        else:
            prompts.append(rng.randint(
                1, cfg.vocab_size,
                (int(rng.randint(3, 13)),)).astype(np.int64))

    # warmup: one full pass over the prompt set — the churn loop replays
    # exactly these prompts, so every executable the measured leg can
    # touch (prefill, suffix prefill, chunk depths, COW copy) is built
    # here and the post-warmup miss gate is airtight
    for p in prompts:
        engine.submit(p)
    engine.drain()
    # second pass over the first batch: a now-fully-cached prompt admits
    # through the zero-prefill + COW path, building the one executable a
    # single cold pass cannot reach
    for p in prompts[:2]:
        engine.submit(p)
    engine.drain()

    failures = []
    miss0 = compile_cache_misses()
    srv = engine.serve_telemetry()
    scraper = Scraper(srv)
    scraper.start()

    # churn under the concurrent scraper, checking conservation between
    # batches (host-side: the census walk itself must not compile)
    worst_frac = 0.0
    checks = 0
    t0 = time.perf_counter()
    try:
        B = engine.config.max_batch
        for b in range(max(args.batches, 1)):
            for i in range(B):
                engine.submit(prompts[(b * B + i) % len(prompts)])
            engine.drain()
            c = ledger.census()
            alloc, unattr = c["allocated_bytes"], c["unattributed_bytes"]
            if alloc is None:
                failures.append("census returned no allocator view")
                break
            checks += 1
            frac = abs(unattr) / max(alloc, 1)
            worst_frac = max(worst_frac, frac)
            if frac > args.tolerance_frac:
                failures.append(
                    f"conservation violated at batch {b}: "
                    f"|unattributed| {unattr}B is "
                    f"{frac * 100:.1f}% of allocated {alloc}B "
                    f"(tolerance {args.tolerance_frac * 100:.0f}%)")
                break
    finally:
        churn_s = time.perf_counter() - t0
        scraper.stop.set()
        scraper.join(timeout=5)

    if scraper.errors:
        failures.append(f"endpoint validation failed: "
                        f"{scraper.errors[0]}")
    if scraper.scrapes < 1:
        failures.append("scraper completed zero full /memz passes")
    dm = compile_cache_misses() - miss0
    if dm:
        failures.append(f"{dm} jit cache misses post-warmup with /memz "
                        f"scraped concurrently (must be 0)")
    srv.close()

    # forced allocation failure -> post-mortem artifact -> oom_report
    pm_dir = tempfile.mkdtemp(prefix="memz_smoke_oom_")
    ledger.postmortem_dir = pm_dir
    engine.chaos = Injector(faults=[AllocFailure()])
    engine.submit(prompts[0])
    oom_seen = False
    try:
        while engine.busy:
            engine.step()
    except RuntimeError as e:
        oom_seen = "RESOURCE_EXHAUSTED" in str(e)
    if not oom_seen:
        failures.append("injected AllocFailure did not surface as a "
                        "RESOURCE_EXHAUSTED step error")
    if not engine.chaos.fired("alloc_failure"):
        failures.append("AllocFailure never fired (vacuous OOM leg)")
    engine.chaos = None
    artifacts = [os.path.join(pm_dir, n) for n in sorted(
        os.listdir(pm_dir)) if n.endswith(".jsonl")]
    if len(artifacts) != 1:
        failures.append(f"expected exactly 1 post-mortem artifact, "
                        f"found {len(artifacts)}")
    largest = None
    if artifacts:
        with open(artifacts[0]) as f:
            head = json.loads(f.readline())
        largest = head.get("oom", {}).get("largest_owner")
        if not largest:
            failures.append("post-mortem head row names no largest "
                            "owner")
        proc = subprocess.run(
            [sys.executable, os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "oom_report.py"),
             artifacts[0]],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if proc.returncode != 0:
            failures.append(f"oom_report.py exited {proc.returncode}: "
                            f"{proc.stderr.strip()[:200]}")
        elif "kv_pool" not in proc.stdout:
            failures.append("oom_report rendering names no kv_pool "
                            "owner")
    # the engine must stay servable after the OOM recovery path
    r = engine.submit(prompts[1])
    engine.drain()
    if r.status != "done":
        failures.append(f"engine not servable after injected OOM "
                        f"(status {r.status})")

    # oversubscription: a pool too small for the concurrent load emits
    # paired mem_pressure episode rows
    tiny = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4,
        paged=True, kv_block=4, kv_blocks=6))
    tiny.attach_memory_ledger()
    rows = []
    tiny.metrics.on_record = rows.append
    for _ in range(4):
        tiny.submit(rng.randint(1, cfg.vocab_size,
                                (10,)).astype(np.int64))
    tiny.drain()
    n_enter = sum(1 for r_ in rows if "mem_pressure" in r_)
    n_clear = sum(1 for r_ in rows if "mem_pressure_clear" in r_)
    if n_enter < 1 or n_enter != n_clear:
        failures.append(f"mem_pressure episodes malformed: "
                        f"{n_enter} enter vs {n_clear} clear rows")

    out = {"scrapes": scraper.scrapes,
           "conservation_checks": checks,
           "worst_unattributed_frac": round(worst_frac, 4),
           "tolerance_frac": args.tolerance_frac,
           "post_warmup_jit_misses": dm,
           "churn_wall_s": round(churn_s, 2),
           "oom_largest_owner": largest,
           "mem_pressure_episodes": n_enter,
           "ok": not failures, "failures": failures}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"memz_smoke: {checks} conservation checks under churn, "
              f"worst |unattributed| {worst_frac * 100:.2f}% of "
              f"allocated (tolerance {args.tolerance_frac * 100:.0f}%), "
              f"{scraper.scrapes} concurrent /memz passes, "
              f"{dm} post-warmup jit misses")
        print(f"memz_smoke: injected OOM -> post-mortem names "
              f"'{largest}', oom_report renders it; "
              f"{n_enter} mem_pressure episodes (paired)")
    for f in failures:
        print(f"memz_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("memz_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
