#!/usr/bin/env python
"""Replay a paddle_tpu numerics anomaly dump standalone.

When a TrainStep with numerics enabled hits a NaN/Inf (or any other
NumericsEvent) it writes the offending batch, parameters, optimizer state,
RNG key and stats tree to ``<dump_dir>/step<K>_<kind>/``. This CLI rebuilds
the model, loads that state and re-runs the step's forward+backward with
the per-layer sentinels installed — reproducing the same bad value and
printing which layer produced it.

    python tools/replay_dump.py dumps/step7312_nan \
        --model my_project.train:build_model [--no-grads] [--json]

``--model pkg.mod:factory`` names a zero-arg callable returning either
``(model, loss_fn)`` or just the model (then --loss names the loss factory
``pkg.mod:fn`` where fn(model) -> loss_fn, or the model itself is called
as ``loss = model(*batch)``).

Exit status: 0 when the replay reproduces the dumped non-finite rows
(or the dump had none), 1 on a mismatch.
"""
from __future__ import annotations

import argparse
import importlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _resolve(spec: str):
    mod, _, attr = spec.partition(":")
    if not attr:
        raise SystemExit(f"--model/--loss must be 'pkg.mod:callable', got {spec!r}")
    return getattr(importlib.import_module(mod), attr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("dump_dir", help="one dump directory (step<K>_<kind>/)")
    ap.add_argument("--model", required=True,
                    help="pkg.mod:factory -> model or (model, loss_fn)")
    ap.add_argument("--loss", default=None,
                    help="pkg.mod:fn with fn(model) -> loss_fn(*batch)")
    ap.add_argument("--no-grads", action="store_true",
                    help="forward only (skip backward / grad rows)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)

    from paddle_tpu import debugging

    dump = debugging.load_dump(args.dump_dir)
    factory = _resolve(args.model)
    built = factory()
    if isinstance(built, tuple):
        model, loss_fn = built
    else:
        model = built
        if args.loss:
            loss_fn = _resolve(args.loss)(model)
        else:
            loss_fn = model
    res = debugging.replay(dump, model, loss_fn,
                           compute_grads=not args.no_grads)

    if args.json:
        print(json.dumps({
            "dump": args.dump_dir,
            "step": dump.step,
            "dumped_events": dump.events,
            "replay_loss": res.loss,
            "matches": res.matches,
            "stats": res.stats.to_dict() if res.stats else None,
            "replay_events": [e.to_dict() for e in res.events],
        }, indent=2))
    else:
        print(f"dump {args.dump_dir} (step {dump.step})")
        print(f"  dumped events : " + "; ".join(
            f"{e['kind']}@{e.get('path')}" for e in dump.events))
        print(f"  replay loss   : {res.loss}")
        if res.stats is not None:
            bad = res.stats.nonfinite_rows()
            if bad:
                print("  reproduced non-finite rows:")
                for p, r in bad:
                    print(f"    {p}: {int(r['nan'])} NaN / {int(r['inf'])} Inf")
            else:
                print("  no non-finite rows reproduced")
            print()
            print(res.stats.format())
        print(f"  matches dump  : {res.matches}")
    return 0 if res.matches in (True, None) else 1


if __name__ == "__main__":
    raise SystemExit(main())
