#!/usr/bin/env python
"""Bench trajectory — fold BENCH_r*.json into one per-row trend table.

Every PR's bench run lands in its own BENCH_rNN.json; reading the ladder's
history means opening six loose files and eyeballing. This tool makes the
trajectory a first-class artifact: one row per bench target (gpt-1.3b,
resnet50, decode-paged, ...), one column per revision, showing tokens/sec
(the row's `value`), ms/step and recompiles — plus a regression gate:

    python tools/bench_history.py                # table over BENCH_r*.json
    python tools/bench_history.py --row resnet50 --json
    python tools/bench_history.py --regress-pct 10   # exit 1 when any
        # row's newest value dropped more than 10% vs its previous
        # recorded revision

Bench rows are identified by their `extra.row` / `row` key when present
(r04+), else by the metric string (r01-r03 predate row names). The files
are driver snapshots whose `tail` holds the bench's JSONL lines — and, for
some revisions, a truncated JSON array — so extraction scans for balanced
JSON objects carrying `metric` + `value` rather than trusting any one
format. Values are throughput-like by convention (tokens/s / images/s:
HIGHER is better); the gate only fires on drops.

Exit status: 0 = ok (or no gate requested), 1 = regression over the gate,
2 = no bench rows found.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional


def _scan_objects(text: str) -> List[dict]:
    """Every balanced {...} JSON object in `text` that parses. Handles
    whole JSONL lines, objects embedded in a (possibly head-truncated)
    JSON array, and noise between them."""
    out = []
    depth = 0
    start = None
    in_str = False
    esc = False
    for i, ch in enumerate(text):
        if in_str:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}":
            if depth > 0:
                depth -= 1
                if depth == 0 and start is not None:
                    try:
                        obj = json.loads(text[start:i + 1])
                        if isinstance(obj, dict):
                            out.append(obj)
                    except json.JSONDecodeError:
                        pass
                    start = None
    return out


def _bench_rows(obj: dict) -> List[dict]:
    """Normalize one scanned object into 0+ bench rows. A row needs
    `metric` + numeric `value`; nested shapes (the `parsed` snapshot, an
    `extra` dict) are flattened into one flat row dict."""
    rows = []
    queue = [obj]
    while queue:
        o = queue.pop()
        if not isinstance(o, dict):
            continue
        if "metric" in o and isinstance(o.get("value"), (int, float)):
            extra = o.get("extra") if isinstance(o.get("extra"), dict) \
                else {}
            flat = {**extra, **{k: v for k, v in o.items()
                                if k != "extra"}}
            rows.append(flat)
        else:
            queue.extend(v for v in o.values() if isinstance(v, dict))
    return rows


def _row_key(row: dict) -> str:
    name = row.get("row")
    if name:
        return str(name)
    # r01-r03 predate row names: normalize the metric string down to a
    # stable key (strip the parenthesized config, collapse spaces)
    metric = str(row.get("metric", "?"))
    return re.sub(r"\s+", " ", re.sub(r"\(.*?\)", "", metric)).strip()


def load_history(paths: List[str]) -> Dict[str, Dict[str, dict]]:
    """{row_key: {revision: row}} over the given BENCH files; revision =
    the file's rNN stem (BENCH_r04.json -> r04), ordered by name."""
    history: Dict[str, Dict[str, dict]] = {}
    for path in sorted(paths):
        rev = os.path.splitext(os.path.basename(path))[0]
        rev = rev[len("BENCH_"):] if rev.startswith("BENCH_") else rev
        with open(path) as f:
            try:
                doc = json.load(f)
            except json.JSONDecodeError:
                doc = None
        texts = []
        if isinstance(doc, dict):
            texts.append(doc.get("tail") or "")
            if isinstance(doc.get("parsed"), dict):
                texts.append(json.dumps(doc["parsed"]))
        else:
            with open(path) as f:
                texts.append(f.read())
        seen_keys = set()
        for text in texts:
            for obj in _scan_objects(text):
                for row in _bench_rows(obj):
                    key = _row_key(row)
                    if (key, rev) in seen_keys:
                        continue    # tail + parsed double-report a row
                    seen_keys.add((key, rev))
                    history.setdefault(key, {})[rev] = row
    return history


def _fmt(v, nd=1) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def trend_table(history: Dict[str, Dict[str, dict]],
                revisions: List[str]) -> str:
    lines = ["---- bench trajectory "
             f"({len(history)} rows x {len(revisions)} revisions) ----"]
    width = max(len(k) for k in history) if history else 4
    hdr = f"  {'row':<{width}}  " + "  ".join(f"{r:>12}"
                                              for r in revisions)
    lines.append(hdr)
    for key in sorted(history):
        revs = history[key]
        cells = []
        for r in revisions:
            row = revs.get(r)
            cells.append(f"{_fmt(row.get('value')):>12}" if row
                         else f"{'-':>12}")
        lines.append(f"  {key:<{width}}  " + "  ".join(cells))
        sub = []
        for metric, nd in (("step_ms", 2), ("recompiles", 0),
                           ("steady_recompiles", 0),
                           # dp scale-out rows (ISSUE 20): efficiency and
                           # exposed collective time trend alongside tok/s
                           ("scaling_efficiency", 3),
                           ("overlap_ratio", 3), ("exposed_s", 4)):
            vals = [revs.get(r, {}).get(metric) for r in revisions]
            if any(v is not None for v in vals):
                sub.append((metric, [f"{_fmt(v, nd):>12}"
                                     if v is not None else f"{'-':>12}"
                                     for v in vals]))
        for metric, cells in sub:
            lines.append(f"    {metric:<{width - 2}}  " + "  ".join(cells))
    return "\n".join(lines)


def check_regressions(history: Dict[str, Dict[str, dict]],
                      revisions: List[str],
                      regress_pct: float) -> List[dict]:
    """Newest recorded value per row vs the previous recorded revision:
    a drop beyond `regress_pct` percent is a violation. Rows recorded at
    only one revision have no baseline and pass."""
    violations = []
    for key in sorted(history):
        revs = [(r, history[key][r]) for r in revisions
                if r in history[key]]
        if len(revs) < 2:
            continue
        (prev_rev, prev), (new_rev, new) = revs[-2], revs[-1]
        pv, nv = prev.get("value"), new.get("value")
        if not pv or nv is None:
            continue
        drop_pct = (pv - nv) / pv * 100.0
        if drop_pct > regress_pct:
            violations.append({"row": key, "prev_rev": prev_rev,
                               "new_rev": new_rev,
                               "prev_value": pv, "new_value": nv,
                               "drop_pct": round(drop_pct, 2)})
    return violations


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("files", nargs="*",
                    help="BENCH json files (default: BENCH_r*.json next "
                         "to the repo root)")
    ap.add_argument("--row", help="only this bench row")
    ap.add_argument("--regress-pct", type=float, default=None,
                    help="fail (exit 1) when a row's newest value drops "
                         "more than this percent vs its previous "
                         "recorded revision")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    files = args.files or sorted(glob.glob(os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_r*.json")))
    if not files:
        print("bench_history: no BENCH files found", file=sys.stderr)
        return 2
    history = load_history(files)
    if args.row:
        history = {k: v for k, v in history.items() if k == args.row}
    if not history:
        print("bench_history: no bench rows parsed", file=sys.stderr)
        return 2
    revisions = sorted({r for revs in history.values() for r in revs})

    violations = []
    if args.regress_pct is not None:
        violations = check_regressions(history, revisions,
                                       args.regress_pct)

    if args.json:
        print(json.dumps({"revisions": revisions,
                          "rows": {k: {r: row for r, row in revs.items()}
                                   for k, revs in history.items()},
                          "violations": violations}, indent=2))
    else:
        print(trend_table(history, revisions))
        for v in violations:
            print(f"bench_history: REGRESSION: {v['row']} "
                  f"{v['prev_value']} ({v['prev_rev']}) -> "
                  f"{v['new_value']} ({v['new_rev']}): "
                  f"-{v['drop_pct']}% over the "
                  f"{args.regress_pct}% gate", file=sys.stderr)
        if args.regress_pct is not None and not violations:
            print(f"bench_history: no row dropped more than "
                  f"{args.regress_pct}% at head")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
