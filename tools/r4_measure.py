"""Round-4 measurement batch (run ALONE on the TPU — concurrent compiles
can kill the relay helper).

Rows: flagship with/without the fused small-param optimizer apply,
ViT-L at B=64, decode bf16 vs int8 weights. One process, sequential,
gc between rows; prints one JSON line per row.
"""
import gc
import json
import os
import sys

sys.path.insert(0, ".")

import bench


def main():
    which = sys.argv[1:] or ["flagship_ab", "flagship_q8", "vit64",
                             "decode_ab"]

    if "flagship_ab" in which:
        os.environ["PADDLE_TPU_FUSE_SMALL_UPDATES"] = "262144"
        r = bench.bench_gpt(True)
        r["extra"]["variant"] = "fused-small-updates"
        print(json.dumps({"variant": "flagship fused", "v": r["value"],
                          "mfu": r["extra"]["mfu"]}), flush=True)
        gc.collect()
        os.environ["PADDLE_TPU_FUSE_SMALL_UPDATES"] = "0"
        r = bench.bench_gpt(True)
        print(json.dumps({"variant": "flagship loop", "v": r["value"],
                          "mfu": r["extra"]["mfu"]}), flush=True)
        os.environ.pop("PADDLE_TPU_FUSE_SMALL_UPDATES", None)
        gc.collect()

    if "flagship_q8" in which:
        # moment traffic at bf16 is ~10GB/step of the flagship's HBM
        # budget; blockwise-int8 moments (the 2.7B fit mechanism) halve it
        r = bench.bench_gpt(True, moment_dtype="int8")
        print(json.dumps({"variant": "flagship int8-moments",
                          "v": r["value"], "mfu": r["extra"]["mfu"],
                          "loss": r["extra"]["loss"]}), flush=True)
        gc.collect()

    if "vit64" in which:
        os.environ["PADDLE_TPU_BENCH_B"] = "64"
        r = bench.bench_vit(True)
        print(json.dumps({"variant": "vit B=64", "v": r["value"],
                          "mfu": r["extra"]["mfu"]}), flush=True)
        os.environ.pop("PADDLE_TPU_BENCH_B", None)
        gc.collect()

    if "decode_ab" in which:
        r = bench.bench_decode(True)
        print(json.dumps({"variant": "decode bf16", "v": r["value"]}),
              flush=True)
        gc.collect()
        os.environ["PADDLE_TPU_BENCH_DECODE_W8"] = "1"
        r = bench.bench_decode(True)
        print(json.dumps({"variant": "decode int8", "v": r["value"]}),
              flush=True)
        os.environ.pop("PADDLE_TPU_BENCH_DECODE_W8", None)


if __name__ == "__main__":
    main()
