#!/usr/bin/env python
"""Active-probing smoke (ISSUE 19) — the tier-1 gate for golden-canary
correctness sentinels: three in-process toy replicas behind the
FleetRouter, each served by a TelemetryServer whose poller drives the
Prober at 2 Hz CONCURRENTLY with closed-loop user decode, then one
silently corrupted KV block the sentinels must catch:

  1. clean interleaved phase: probes ride the real submit()/step path
     while user traffic drains — zero probe failures, zero deep
     invariant violations, and ZERO post-warmup jit cache misses with
     the prober attached (warm() pre-lowered every probe executable);
  2. probe/SLO isolation: probe requests never touch the user-facing
     request counters or rejection totals on any replica;
  3. the fleet surface merges: /fleet/probez reports every prober
     passing, one config fingerprint fleet-wide, no drift finding;
  4. CorruptKVBlock flips bytes inside the victim's cached probe block
     — no exception, no accounting change, invisible to the invariant
     audits — and the next probe cycle catches it: EXACTLY ONE
     structured {"probe_fail"} row (the transition machine holds while
     the failure is sustained) and a pinned flight-recorder capture;
  5. router.step() consults the probers and ejects the failing replica
     like a dead one (probe_ejected=1) while the remaining fleet keeps
     serving bit-identically to the fault-free oracle and the fleet
     page keeps answering with the victim marked failing.

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/probe_smoke.py [--requests 24] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures",
    "mini_step.trace.json.gz")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=24,
                    help="shared-prefix user requests in the clean leg")
    ap.add_argument("--seed", type=int, default=7,
                    help="traffic/corruption seed")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import (BlockPool, FleetRouter,
                                      ReplicaRegistry, ServingConfig,
                                      ServingEngine)
    from paddle_tpu.inference.serving import shared_prefix_traffic
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.obs import (FixtureBackend, FleetAggregator,
                                FlightRecorder, GoldenStore, Prober)
    from paddle_tpu.resilience import CorruptKVBlock, Injector

    paddle.seed(0)
    gcfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                     num_heads=2, max_position_embeddings=64,
                     intermediate_size=64)
    model = GPTForCausalLM(gcfg)
    model.eval()
    KB = 4
    BPB = BlockPool.for_model(model, num_blocks=2,
                              block_size=KB).bytes_per_block

    def mk() -> ServingEngine:
        # spill tier configured (warmup lowers the d2h gather / h2d
        # scatter pair CorruptKVBlock's read/write round-trip reuses)
        # but the prefix budget is GENEROUS: the corrupted probe block
        # must stay resident until the sentinel attends it — eviction
        # churn would let the cache self-heal before detection
        return ServingEngine(model, ServingConfig(
            max_batch=2, prompt_cap=16, max_new_tokens=6, decode_chunk=3,
            paged=True, prefix_cache=True, kv_block=KB, kv_blocks=48,
            prefix_cache_bytes=64 * BPB, spill_host_bytes=1 << 22))

    traffic = shared_prefix_traffic(
        args.requests, n_prefixes=3, prefix_len=2 * KB, prompt_cap=16,
        vocab_size=gcfg.vocab_size, rate=1e9, seed=args.seed)
    prompts = [t["prompt"] for t in traffic]
    post_prompts = prompts[: max(3, len(prompts) // 4)]

    failures = []

    # ---------------------------------------------- fault-free oracle
    oracle_eng = mk()
    oracle = {}
    for p in prompts:
        r = oracle_eng.submit(p)
        oracle_eng.drain()
        if r.status != "done":
            failures.append(f"oracle refused a prompt: {r.reason}")
        oracle[p.tobytes()] = r.tokens

    # --------------------------------------------------- fleet + probers
    reg = ReplicaRegistry({f"r{i}": mk() for i in range(3)})
    router = FleetRouter(reg, policy="prefix", retry_budget_s=5.0,
                         seed=args.seed)
    # ONE lock serializes every engine call fleet-wide: the poller
    # threads (probe cycles, invariant audits) and this driver's step
    # loop share it, per the engine's one-lock threading contract
    lock = threading.Lock()
    store = GoldenStore()                # shared: one golden per variant
    for h in reg.handles():
        h.engine.warmup_prefix_cache(gcfg.vocab_size)
        h.prober = Prober(h.engine, store=store, replica=h.name,
                          lock=lock).warm()
    miss0 = compile_cache_misses()
    # user-facing accounting baseline AFTER warmup (warmup submits are
    # real user-path requests) — the probe storm must not move it
    req0 = sum(h.engine.metrics.counters["requests"]
               for h in reg.handles())
    rej0 = sum(h.engine.metrics.counters["rejected"]
               for h in reg.handles())

    servers = {}
    for h in reg.handles():
        servers[h.name] = h.engine.serve_telemetry(
            prober=h.prober, probe_interval=0.5,     # the 2 Hz sentinel
            invariant_interval=0.25)
    agg = FleetAggregator({n: s.url() for n, s in servers.items()},
                          cache_ttl=0.0)

    try:
        # ------------------------- clean leg: probes ride live traffic
        cyc0 = {h.name: h.prober.cycles_total for h in reg.handles()}
        with lock:
            freqs = [router.submit(p) for p in prompts]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with lock:
                router.step()
                busy = any(f.status == "pending" for f in freqs)
            if not busy:
                break
            time.sleep(0.001)
        # ... and keep serving probes only until every sentinel ran at
        # least 2 cycles concurrently with (or right after) the traffic
        while time.monotonic() < deadline and any(
                h.prober.cycles_total - cyc0[h.name] < 2
                for h in reg.handles()):
            time.sleep(0.05)

        bad = [f for f in freqs if f.status != "done"]
        if bad:
            failures.append(f"{len(bad)} user requests did not complete: "
                            f"{[(f.status, f.reason) for f in bad[:3]]}")
        mismatch = sum(1 for f in freqs if f.status == "done" and not
                       np.array_equal(f.tokens, oracle[f.prompt.tobytes()]))
        if mismatch:
            failures.append(f"{mismatch} clean-leg requests differ from "
                            f"the oracle (must be bit-identical)")
        dm = compile_cache_misses() - miss0
        if dm:
            failures.append(f"{dm} post-warmup jit cache misses with the "
                            f"2 Hz prober attached (must be 0)")
        for h in reg.handles():
            pz = h.prober.probez()
            if pz["state"] != "passing" or pz["failures_total"]:
                failures.append(f"{h.name}: clean-leg probe state "
                                f"{pz['state']} (failures="
                                f"{pz['failures_total']})")
            inv = pz.get("invariants", {})
            if inv.get("violating") or not inv.get("audits_total"):
                failures.append(f"{h.name}: invariant audits "
                                f"{'violating' if inv.get('violating') else 'never ran'}")
            if h.engine.metrics.probe_counters["requests"] < 1:
                failures.append(f"{h.name}: no probe request was "
                                f"accounted on the probe side")
        # probe/SLO isolation: dozens of probe cycles ran, yet the
        # user-facing request/rejection counters only ever saw the
        # user traffic itself
        user_reqs = sum(h.engine.metrics.counters["requests"]
                        for h in reg.handles()) - req0
        user_rej = sum(h.engine.metrics.counters["rejected"]
                       for h in reg.handles()) - rej0
        if user_reqs != len(freqs) or user_rej:
            failures.append(f"probe traffic leaked into user accounting "
                            f"(requests={user_reqs} want {len(freqs)}, "
                            f"rejected={user_rej} want 0)")
        if store.minted_total != len(next(iter(
                reg.handles())).prober.variants):
            failures.append(f"goldens minted {store.minted_total} times "
                            f"for a 3-replica fleet sharing one "
                            f"fingerprint (must be once per variant)")

        fp = agg.fleet_probez()
        if fp["summary"]["with_prober"] != 3 or fp["summary"]["failing"]:
            failures.append(f"clean fleet page wrong: {fp['summary']}")
        if fp["summary"]["config_drift"] or \
                len(set(fp["summary"]["fingerprints"].values())) != 1:
            failures.append(f"config drift flagged on an identical "
                            f"fleet: {fp['summary']['fingerprints']}")
        page = agg.merged_metrics()
        if "paddle_tpu_probe_cycles_total" not in page or \
                "paddle_tpu_invariant_audits_total" not in page:
            failures.append("merged fleet /metrics page is missing the "
                            "probe_*/invariant_* families")

        # --------------------- corruption leg: one silently bad block
        victim = "r1"
        vh = reg.handle(victim)
        veng, vp = vh.engine, vh.prober
        rec = FlightRecorder(tempfile.mkdtemp(prefix="probe_smoke_"),
                             backend=FixtureBackend(FIXTURE),
                             trigger_steps=1, cooldown_s=0.0)
        rows = []
        with lock:
            rec.attach(monitor=veng.monitor, metrics=veng.metrics)
            prev = veng.metrics.on_record
            veng.metrics.on_record = lambda r: (prev(r), rows.append(r))
            blks = vp.probe_blocks("prefix_hit")
            if not blks:
                failures.append(f"{victim}: no cached probe block to "
                                f"corrupt (trie empty?)")
            fault = CorruptKVBlock(engine=veng,
                                   block=blks[0] if blks else None,
                                   seed=args.seed)
            veng.chaos = Injector(args.seed).add(fault)

        # the 2 Hz poller fires the next probe.cycle, the fault flips
        # bytes in-place, the hit-path sentinel attends them: detection
        # within one probe cycle, no driver involvement
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline and not vp.failing:
            time.sleep(0.02)
        fail_cycle = vp.cycles_total
        if not fault.fired or fault.corrupted_block is None:
            failures.append("CorruptKVBlock never fired — the scenario "
                            "tested nothing")
        if not vp.failing:
            failures.append(f"{victim}: sentinel missed the corrupted "
                            f"block entirely")
        # sustained failure stays ONE structured row (transition machine)
        while time.monotonic() < deadline and \
                vp.cycles_total < fail_cycle + 2:
            time.sleep(0.02)
        fail_rows = [r for r in rows if "probe_fail" in r]
        if len(fail_rows) != 1:
            failures.append(f"expected exactly one probe_fail row, got "
                            f"{len(fail_rows)}")
        elif fail_rows[0]["probe_fail"].get("first_divergence") is None:
            failures.append("probe_fail row carries no first_divergence "
                            "position")
        while time.monotonic() < deadline and not \
                any(c.get("pinned") for c in rec.captures):
            time.sleep(0.02)
        caps = [c for c in rec.captures if c.get("pinned")]
        if not caps:
            failures.append("no pinned flight-recorder capture for the "
                            "probe failure")
        elif "probe_fail" not in [t["kind"] for c in caps
                                  for t in c["triggers"]]:
            failures.append("pinned capture was not triggered by "
                            "probe_fail")

        # --------------------------- ejection: fleet drops the replica
        with lock:
            router.step()
        if router.counters["probe_ejected"] != 1:
            failures.append(f"probe_ejected="
                            f"{router.counters['probe_ejected']}, "
                            f"expected 1")
        if victim not in reg.ejected:
            failures.append(f"{victim} still in the fleet after a "
                            f"correctness failure")
        elif not reg.ejected[victim].ejected_reason.startswith(
                "probe_fail:"):
            failures.append(f"ejection reason "
                            f"{reg.ejected[victim].ejected_reason!r} "
                            f"does not name the failing probe")
        if len(reg.names(("serving",))) != 2:
            failures.append(f"fleet did not keep serving on 2 replicas "
                            f"(serving={reg.names(('serving',))})")

        with lock:
            preqs = [router.submit(p) for p in post_prompts]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            with lock:
                router.step()
                busy = any(f.status == "pending" for f in preqs)
            if not busy:
                break
            time.sleep(0.001)
        pbad = sum(1 for f in preqs if f.status != "done" or not
                   np.array_equal(f.tokens, oracle[f.prompt.tobytes()]))
        if pbad:
            failures.append(f"{pbad}/{len(preqs)} post-ejection requests "
                            f"not served bit-identically by the "
                            f"surviving fleet")

        fp2 = agg.fleet_probez()
        if fp2["summary"]["failing"] != [victim]:
            failures.append(f"fleet page after ejection should mark "
                            f"{victim} failing, got "
                            f"{fp2['summary']['failing']}")
        if fp2["summary"]["answered"] < 2:
            failures.append("fleet page stopped answering during the "
                            "ejection")
        with lock:
            rec.detach()
            veng.chaos = None
    finally:
        for s in servers.values():
            s.close()

    out = {"requests": len(prompts),
           "completed": sum(1 for f in freqs if f.status == "done"),
           "probe_cycles": {h.name: h.prober.cycles_total
                            for h in list(reg.handles()) +
                            list(reg.ejected.values())},
           "goldens_minted": store.minted_total,
           "post_warmup_jit_misses": compile_cache_misses() - miss0,
           "probe_fail_rows": len(fail_rows),
           "pinned_captures": len(caps),
           "probe_ejected": router.counters["probe_ejected"],
           "post_ejection_ok": len(post_prompts) - pbad,
           "ok": not failures, "failures": failures}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"probe_smoke: {out['completed']}/{out['requests']} user "
              f"requests bit-identical with 2 Hz probes interleaved; "
              f"{out['goldens_minted']} goldens for 3 replicas; "
              f"corruption -> {out['probe_fail_rows']} probe_fail row, "
              f"{out['pinned_captures']} pinned capture(s), "
              f"probe_ejected={out['probe_ejected']}; "
              f"{out['post_ejection_ok']}/{len(post_prompts)} served "
              f"bit-identically after ejection")
    for f in failures:
        print(f"probe_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("probe_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
