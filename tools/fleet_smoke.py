#!/usr/bin/env python
"""Fleet-observability smoke (ISSUE 13) — the tier-1 gate for the fleet
aggregation layer: boot THREE in-process toy serving replicas, each with
its own TelemetryServer, aggregate them through a FleetAggregator, and
prove the fleet surface end-to-end:

  1. the merged exposition page stays LINT-CLEAN while a scraper thread
     re-aggregates at 10 Hz concurrently with live decode traffic on all
     three replicas (counters summed, gauges replica-labeled, histograms
     pooled bucket-wise);
  2. the fleet p99 (e2e) derived from the MERGED page's pooled buckets
     matches the pooled oracle: a single LogHistogram fed every raw
     latency from every replica (bucket-exact), which itself sits within
     bucket resolution of the raw numpy percentile;
  3. one replica KILLED mid-run is reported stale in /fleet/healthz and
     the fleet block while the merged page keeps serving from the two
     survivors — the aggregator never answers a scrape with a 500
     because a member died;
  4. zero post-warmup jit cache misses across every replica with both
     telemetry layers attached (replica scrape + fleet re-scrape must
     never compile);
  5. the /fleet/tracez merge answers with trace_id-unique rows from the
     surviving members.

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/fleet_smoke.py [--batches 8] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


class FleetScraper(threading.Thread):
    """Re-aggregate + validate the fleet surface in a loop: merged page
    lints, /fleet/healthz parses with the rollup keys, /fleet/tracez
    answers. Runs for the duration of the traffic."""

    def __init__(self, fleet_srv, interval: float = 0.1):
        super().__init__(name="fleet-smoke-scraper", daemon=True)
        self.srv = fleet_srv
        self.interval = interval
        self.stop = threading.Event()
        self.scrapes = 0
        self.errors = []

    def _one_pass(self):
        from urllib.error import HTTPError
        from urllib.request import urlopen
        from paddle_tpu.obs import lint_exposition
        try:
            text = urlopen(self.srv.url("/metrics"),
                           timeout=5).read().decode()
        except HTTPError as e:
            raise AssertionError(f"fleet /metrics {e.code}: "
                                 f"{e.read().decode()[:300]}") from e
        lint_exposition(text)
        h = json.loads(urlopen(self.srv.url("/fleet/healthz"),
                               timeout=5).read())
        for key in ("status", "replicas", "serving", "stale",
                    "queue_depth", "overloaded_total"):
            if key not in h:
                raise AssertionError(f"/fleet/healthz missing {key}")
        t = json.loads(urlopen(self.srv.url("/fleet/tracez?limit=8"),
                               timeout=5).read())
        if "summary" not in t or "traces" not in t:
            raise AssertionError("/fleet/tracez missing summary/traces")

    def run(self):
        while not self.stop.is_set():
            try:
                self._one_pass()
                self.scrapes += 1
            except Exception as e:             # noqa: BLE001 — the gate
                self.errors.append(f"{type(e).__name__}: {e}")
                return
            if self.stop.wait(timeout=self.interval):
                return


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--batches", type=int, default=8,
                    help="full micro-batches of traffic per replica "
                         "(half before the kill, half after)")
    ap.add_argument("--scrape-interval", type=float, default=0.1,
                    help="seconds between fleet aggregation passes")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.inference.serving import ServingMetrics
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.obs import (FleetAggregator, bucket_percentile,
                                lint_exposition)
    from paddle_tpu.profiler._metrics import LogHistogram

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128)
    # one toy model, three replicas: identical executables, so warmup on
    # the first replica warms them all and the global compile-miss
    # counter covers every replica at once
    model = GPTForCausalLM(cfg)
    model.eval()

    raw_e2e = [[], [], []]      # per-replica raw latency streams — the
    #                             pooled-numpy oracle's input

    def hook_for(i):
        def hook(row):
            e2e = (row.get("request") or {}).get("e2e_s")
            if e2e is not None:
                raw_e2e[i].append(float(e2e))
        return hook

    engines, servers = [], []
    for i in range(3):
        eng = ServingEngine(model, ServingConfig(
            max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4),
            metrics=ServingMetrics(on_record=hook_for(i)))
        engines.append(eng)
        servers.append(eng.serve_telemetry())
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (int(rng.randint(3, 13)),)).astype(np.int64)
               for _ in range(16)]

    # warmup the shared executable set through every replica (each
    # replica still runs its own warmup batch: per-engine host state)
    for eng in engines:
        for p in prompts[:2]:
            eng.submit(p)
        eng.drain()

    failures = []
    miss0 = compile_cache_misses()

    # cache_ttl=0: this smoke asserts staleness TRANSITIONS right after
    # the kill — the scrape-storm TTL cache (ISSUE 14, default 1s) would
    # serve the pre-kill snapshot; the cache has its own unit tests
    fleet = FleetAggregator(
        {f"replica{i}": srv for i, srv in enumerate(servers)},
        timeout=2.0, cache_ttl=0.0)
    fleet_srv = fleet.serve()
    scraper = FleetScraper(fleet_srv, interval=args.scrape_interval)
    scraper.start()

    def run_block(live, batches):
        B = live[0].config.max_batch
        for b in range(batches):
            for eng in live:
                for i in range(B):
                    eng.submit(prompts[(b * B + i) % len(prompts)])
                eng.drain()

    half = max(args.batches // 2, 1)
    run_block(engines, half)

    # kill replica1 mid-run: its server goes away, its engine stops
    # taking traffic; the fleet must degrade, not 500
    servers[1].close()
    run_block([engines[0], engines[2]], half)
    # give the scraper at least one pass over the degraded fleet
    deadline = time.time() + 5.0
    post_kill = scraper.scrapes
    while scraper.scrapes < post_kill + 2 and not scraper.errors \
            and time.time() < deadline:
        time.sleep(0.02)

    scraper.stop.set()
    scraper.join(timeout=5)
    if scraper.errors:
        failures.append(f"fleet surface validation failed: "
                        f"{scraper.errors[0]}")
    if scraper.scrapes < 2:
        failures.append(f"fleet scraper completed {scraper.scrapes} "
                        f"passes (need >= 2: before and after the kill)")

    dm = compile_cache_misses() - miss0
    if dm:
        failures.append(f"{dm} jit cache misses post-warmup across the "
                        f"fleet (must be 0)")

    # stale reporting + merged page still serving, straight from the
    # aggregator (not the HTTP loop, so failures name themselves)
    page = fleet.merged_metrics()
    try:
        lint_exposition(page)
    except Exception as e:                      # noqa: BLE001 — the gate
        failures.append(f"merged page does not lint after kill: {e}")
    if 'paddle_tpu_fleet_up{replica="replica1"} 0' not in page:
        failures.append("killed replica not reported down in fleet block")
    health = fleet.fleet_healthz()
    if health.get("stale") != 1 or health.get("serving") != 2:
        failures.append(f"fleet healthz rollup wrong after kill: "
                        f"{ {k: health.get(k) for k in ('serving', 'draining', 'stale')} }")

    # fleet p99 from the merged page's POOLED buckets vs the oracle:
    # one histogram holding the SURVIVORS' pooled buckets (replica1's
    # page is stale/excluded from the merge), min/max carried so the
    # oracle percentile clamps like a single-recorder stream would
    oracle = LogHistogram(lo=1e-4, hi=1e3, per_decade=10)
    n_oracle = 0
    for eng in (engines[0], engines[2]):
        h = eng.metrics.hists["e2e_seconds"]
        for i, c in enumerate(h.counts):
            oracle.counts[i] += c
        oracle.count += h.count
        oracle.sum += h.sum
        n_oracle += h.count
        oracle._min = h._min if oracle._min is None else \
            min(oracle._min, h._min)
        oracle._max = h._max if oracle._max is None else \
            max(oracle._max, h._max)
    fams = lint_exposition(page)
    fam = fams.get("paddle_tpu_serving_e2e_seconds")
    merged_p99 = oracle_p99 = None
    if fam is None:
        failures.append("merged page missing the pooled e2e histogram")
    else:
        buckets, count = [], 0.0
        for base, labels, val in fam["samples"]:
            if base.endswith("_bucket"):
                le = labels[1:-1].split("=", 1)[1].strip('"')
                buckets.append((float("inf") if le == "+Inf"
                                else float(le), float(val)))
            elif base.endswith("_count"):
                count = float(val)
        merged_p99 = bucket_percentile(sorted(buckets), count, 0.99)
        oracle_p99 = oracle.percentile(0.99)
        if count != n_oracle:
            failures.append(f"merged e2e count {count} != pooled oracle "
                            f"count {n_oracle}")
        # same buckets, same counts -> the derived percentiles may only
        # differ by the recorder's min/max clamp: allow one bucket ratio
        ratio = 10 ** (1 / 10)
        if not (oracle_p99 / ratio <= merged_p99 <= oracle_p99 * ratio):
            failures.append(f"fleet p99 {merged_p99:.6f}s not within one "
                            f"bucket of pooled oracle {oracle_p99:.6f}s")
        # and the pooled-numpy-stream backstop: the merged-page figure
        # must sit within bucket resolution of the raw percentile over
        # the survivors' pooled streams (one bucket for the recorder's
        # quantization + one for interpolation)
        pooled = np.asarray(raw_e2e[0] + raw_e2e[2])
        np_p99 = float(np.percentile(pooled, 99)) if pooled.size else None
        if np_p99 and not (np_p99 / ratio ** 2 <= merged_p99
                           <= np_p99 * ratio ** 2):
            failures.append(f"fleet p99 {merged_p99:.6f}s vs raw pooled "
                            f"numpy p99 {np_p99:.6f}s: outside two "
                            f"bucket ratios")
    out = {"scrapes": scraper.scrapes,
           "requests_pooled": int(n_oracle),
           "merged_p99_s": merged_p99,
           "oracle_p99_s": oracle_p99,
           "post_warmup_jit_misses": dm,
           "stale_replicas": health.get("stale"),
           "ok": not failures, "failures": failures}

    fleet_srv.close()
    fleet.close()
    for srv in (servers[0], servers[2]):
        srv.close()

    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"fleet_smoke: {out['scrapes']} aggregation passes over 3 "
              f"replicas ({out['requests_pooled']} pooled requests); "
              f"fleet p99 {out['merged_p99_s']}s vs oracle "
              f"{out['oracle_p99_s']}s; post-warmup jit misses {dm}; "
              f"replica1 killed -> {out['stale_replicas']} stale")
    for f in failures:
        print(f"fleet_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("fleet_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
