#!/usr/bin/env python
"""Chaos-train driver — prove kill-anywhere + bit-exact resume on a real
GPT train loop, and measure the async-checkpoint overhead (ISSUE 7).

The seed IS the scenario: ``Injector.random_kill(seed, lo, hi)`` derives
the kill step, the data, the shuffle order and the model init from one
integer, so a failing run reproduces from its printed seed alone.

Three phases per scenario:

  oracle     the uninterrupted run — per-step loss trajectory recorded as
             raw float32 (bit comparison, not allclose).
  chaos      same build + a seeded kill: CheckpointManager saves every
             ``--save-every`` steps (async), the injector kills the
             process at a random step boundary (SimulatedKill — a
             BaseException, same as the SIGKILL it models; a save still
             on the writer thread at the kill is rolled back, because a
             real SIGKILL kills the writer too), then the
             driver "restarts": fresh model/optimizer/loader/RNG,
             ``restore_latest()`` (checksum-verified), resume to the end.
             Every step the chaos run produced — including the steps
             REPLAYED between the last checkpoint and the kill — must
             match the oracle bit-for-bit, and every committed checkpoint
             must restore clean.
  overhead   (--overhead) paired interleaved blocks — steps that save
             every ``--overhead-save-every`` vs clean steps from the SAME
             run: the acceptance bar is async_save ≈ free (within ~5% on
             the CPU toy; the host snapshot is the only on-thread work,
             serialization overlaps the next steps on a niced writer
             thread). Note the hard floor is physics: the writer needs
             ~16ms CPU per save, so on a saturated host the cost is
             writer_cpu / (cadence · cores) — pick the cadence you mean.

Exit nonzero on any trajectory divergence, corrupt checkpoint, or (with
--overhead-max-pct) an overhead blow-through. Registered in
tools/run_tier1.sh with its own time budget (check_tiers --chaos-seconds);
the multi-seed sweep lives behind --sweep and is tier-marked slow.

    python tools/chaos_train.py --quick            # tier-1 budget mode
    python tools/chaos_train.py --steps 24 --seed 7 --overhead
    python tools/chaos_train.py --sweep 5          # 5 seeded scenarios
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _build(seed: int, args):
    """One deterministic training world: model, optimizer, loss, loader,
    monitor — everything keyed off `seed`."""
    import paddle_tpu as paddle
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_config
    from paddle_tpu.profiler.monitor import StepMonitor

    paddle.seed(seed)
    cfg = gpt_config("gpt3-125m", hidden_size=args.hidden, num_layers=2,
                     num_heads=2, vocab_size=args.vocab,
                     max_position_embeddings=args.seq_len,
                     hidden_dropout=0.1)
    model = GPTForCausalLM(cfg)

    class TokenDS(Dataset):
        def __init__(self):
            rng = np.random.RandomState(seed + 1)
            self.ids = rng.randint(
                0, args.vocab,
                (args.n_samples, args.seq_len + 1)).astype(np.int64)

        def __getitem__(self, i):
            return self.ids[i, :-1], self.ids[i, 1:]

        def __len__(self):
            return args.n_samples

    loader = DataLoader(TokenDS(), batch_size=args.batch, shuffle=True,
                        seed=seed + 2)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    monitor = StepMonitor(track_memory=False, log_recompiles=False)
    step = TrainStep(model, opt,
                     lambda x, y: model.loss(x, y), monitor=monitor)
    return step, loader, monitor


def _run(step, loader, total_steps, losses, chaos=None, manager=None,
         state=None, save_every=2, async_save=True):
    """Drive `total_steps` steps, recording float32 losses into the
    `losses` dict (step -> [values]); checkpoint every `save_every`."""
    i = step._step_i
    step.chaos = chaos
    while i < total_steps:
        for batch in loader:
            loss = step(*batch)
            i = step._step_i
            losses.setdefault(i, []).append(
                np.float32(np.asarray(loss._data)))
            if manager is not None and i % save_every == 0:
                manager.save(i, state.state_dict(), async_save=async_save)
            if i >= total_steps:
                break
    if manager is not None:
        manager.wait()


def run_scenario(seed: int, args) -> dict:
    """One oracle-vs-chaos comparison; returns the result row. The chaos
    and resume phases each record a goodput timeline segment (ISSUE 8):
    the injected kill must show up in the stitched GoodputReport as
    `restart_downtime` + `replay` badput, with the replayed-step count
    matching the resume delta and conservation holding — the goodput
    verdict rides the same `ok` flag as the bit-exactness one."""
    from paddle_tpu import resilience
    from paddle_tpu.profiler import timeline as tl_mod

    t0 = time.perf_counter()
    # ---- oracle -----------------------------------------------------
    step, loader, _ = _build(seed, args)
    oracle: dict = {}
    _run(step, loader, args.steps, oracle)

    # ---- chaos ------------------------------------------------------
    ckpt_dir = os.path.join(args.ckpt_root, f"seed{seed}")
    shutil.rmtree(ckpt_dir, ignore_errors=True)
    tdir = os.path.join(args.timeline_dir, f"seed{seed}")
    shutil.rmtree(tdir, ignore_errors=True)
    lo = args.save_every + 1
    inj = resilience.Injector.random_kill(seed, lo,
                                          max(lo, args.steps - 1))
    kill_step = inj.kill_step
    mgr = resilience.CheckpointManager(ckpt_dir, keep_last=3,
                                       chaos=None)
    step, loader, _ = _build(seed, args)
    state = resilience.TrainState(train_step=step, loader=loader)
    chaos_losses: dict = {}
    died = False
    rec1 = tl_mod.SpanRecorder(
        os.path.join(tdir, "seg0.timeline.jsonl"),
        meta={"phase": "chaos", "seed": seed,
              "run": f"seed{seed}"})
    try:
        with tl_mod.installed(rec1):
            _run(step, loader, args.steps, chaos_losses, chaos=inj,
                 manager=mgr, state=state, save_every=args.save_every)
    except resilience.SimulatedKill:
        died = True
        # the timeline analog of a real SIGKILL's silence: stamp where
        # the process died so the stitcher can attribute the gap to the
        # next segment as restart_downtime
        rec1.mark_exit("chaos-kill", step=kill_step)
        # fidelity: the kill models a SIGKILL at this instant — a save
        # still on the writer thread must not commit post-mortem, or the
        # "restart" below resumes from a checkpoint a real kill never
        # produced and the proof is weaker than it claims
        with tl_mod.installed(rec1):
            mgr.discard_inflight()
    rec1.close()
    if not died:
        raise AssertionError(
            f"seed {seed}: injector never fired (kill_step={kill_step}, "
            f"steps={args.steps})")

    # ---- restart-and-resume (a fresh "process") ---------------------
    rec2 = tl_mod.SpanRecorder(
        os.path.join(tdir, "seg1.timeline.jsonl"),
        meta={"phase": "resume", "seed": seed,
              "run": f"seed{seed}"})
    with tl_mod.installed(rec2):
        step, loader, monitor = _build(seed, args)
        state = resilience.TrainState(train_step=step, loader=loader,
                                      monitor=monitor)
        try:
            resumed_at, sd = mgr.restore_latest()  # checksum-verified
            state.load_state_dict(sd)
        except FileNotFoundError:
            # the kill outran every commit (possible when the only save
            # was still in flight): a real job restarts from scratch —
            # so do we
            resumed_at = None
        compiles_before = monitor.compiles
        _run(step, loader, args.steps, chaos_losses,
             manager=mgr, state=state, save_every=args.save_every)
    rec2.close()

    # ---- verdicts ---------------------------------------------------
    divergences = []
    for s, vals in sorted(chaos_losses.items()):
        want = oracle.get(s)
        if want is None:
            divergences.append(f"step {s}: chaos ran a step the oracle "
                               f"never did")
            continue
        for v in vals:   # pre-kill AND post-resume replays of this step
            if v.tobytes() != want[0].tobytes():
                divergences.append(
                    f"step {s}: {v!r} != oracle {want[0]!r}")
    # the kill step's loss is lost in-flight; every other step must appear
    missing = [s for s in oracle
               if s not in chaos_losses and s != kill_step]
    if missing:
        divergences.append(f"steps missing from chaos run: {missing}")

    corrupt = []
    for s in mgr.all_steps():
        try:
            mgr.restore(s)
        except resilience.CheckpointCorruptError as e:
            corrupt.append(f"step {s}: {e}")

    # ---- goodput verdict (ISSUE 8): the kill must be VISIBLE --------
    from paddle_tpu.profiler.goodput import ConservationError, GoodputReport
    goodput = None
    try:
        rep = GoodputReport(tl_mod.load_segments(tdir))
        rep.check_conservation()
    except ConservationError as e:
        divergences.append(f"goodput conservation violated: {e}")
        rep = None
    except Exception as e:
        divergences.append(f"goodput report failed: {e!r}")
        rep = None
    if rep is not None:
        s = rep.summary()
        goodput = {"goodput_ratio": s["goodput_ratio"],
                   "restart_downtime_s": s["badput_s"]["restart_downtime"],
                   "replay_s": s["badput_s"]["replay"],
                   "replayed_steps": s["replayed_steps"],
                   "restarts": s["restarts"], "wall_s": s["wall_s"]}
        if s["restarts"] != 1:
            divergences.append(
                f"goodput: expected 1 restart in the stitched timeline, "
                f"got {s['restarts']}")
        if s["badput_s"]["restart_downtime"] <= 0:
            divergences.append(
                "goodput: injected kill left no restart_downtime badput")
        if resumed_at is not None:
            want = kill_step - resumed_at
            if s["replayed_steps"] != want:
                divergences.append(
                    f"goodput: replayed_steps {s['replayed_steps']} != "
                    f"resume delta {want} (kill@{kill_step}, "
                    f"resume@{resumed_at})")

    row = {"seed": seed, "kill_step": kill_step, "resumed_at": resumed_at,
           "steps": args.steps,
           "replayed": resumed_at is not None
           and kill_step - resumed_at,
           "compiles_after_resume": monitor.compiles - compiles_before,
           "goodput": goodput,
           "divergences": divergences, "corrupt": corrupt,
           "wall_s": round(time.perf_counter() - t0, 2),
           "ok": not divergences and not corrupt}
    return row


def run_overhead(seed: int, args) -> dict:
    """Async-save overlap measurement: steady steps checkpointing every
    ``--save-every`` vs clean steps, interleaved block-by-block in ONE
    run (paired design — whole-leg timing measures the neighbors on a
    shared box, not the checkpoint path).

    Uses a compute-dominated config (bigger hidden/seq/batch than the
    chaos scenarios): the claim under test is that serialization overlaps
    the NEXT steps and only the host snapshot runs on the training
    thread — which is only visible when a step costs more than a
    parameter memcpy. Save blocks end in manager.wait(), so nothing
    hides off the clock."""
    import copy
    from paddle_tpu import resilience

    oargs = copy.copy(args)
    oargs.hidden, oargs.seq_len, oargs.batch = 64, 64, 16
    oargs.n_samples = max(args.n_samples,
                          (args.overhead_steps + 4) * oargs.batch)

    # PAIRED, INTERLEAVED measurement: one training run alternating
    # save-blocks and clean-blocks, comparing the two step populations'
    # medians. Sequential whole-leg timing is useless on a shared box —
    # measured baselines here swing 3x between runs as neighbors come and
    # go — but interleaved blocks see the same load regime within any
    # noise window, so the block-to-block DELTA isolates the checkpoint
    # path. Save blocks carry everything the path costs: the on-thread
    # snapshot+dispatch inside their step walls, and an end-of-block
    # wait() so writer-thread work cannot bleed into clean blocks.
    step, loader, _ = _build(seed, oargs)
    d = os.path.join(args.ckpt_root, "overhead")
    shutil.rmtree(d, ignore_errors=True)
    mgr = resilience.CheckpointManager(d, keep_last=2)
    state = resilience.TrainState(train_step=step, loader=loader)
    losses: dict = {}
    warm = 5   # compile + let the first steps' cache/allocator noise
    #            settle (measured: steps 1-5 run up to 2x steady wall)
    _run(step, loader, warm, losses)
    mgr.save(0, state.state_dict(), async_save=True)   # pre-warm IO path
    mgr.wait()

    # cycle = [save block: saves at --save-every, every step sampled]
    #         [1 gap step: mgr.wait() drains the writer, step DISCARDED]
    #         [clean block: sampled] [1 gap step: symmetric, discarded]
    # The gap absorbs residual writer-thread work, so the final save of a
    # block gets its one step of overlap (production shape) without its
    # contention bleeding into the clean samples.
    save_every = args.overhead_save_every
    block = max(2 * save_every, 4)
    cycle = 2 * (block + 1)
    cycles = max(2, args.overhead_steps * args.overhead_trials // cycle)
    base_walls: list = []
    ckpt_walls: list = []
    i = step._step_i
    target = i + cycle * cycles
    k = 0          # step index within the alternating schedule
    while i < target:
        for batch in loader:
            pos = k % cycle
            in_save_block = pos < block
            is_gap = pos == block or pos == cycle - 1
            t0 = time.perf_counter()
            loss = step(*batch)
            np.asarray(loss._data)              # step complete on host
            i = step._step_i
            k += 1
            if in_save_block and k % save_every == 0:
                mgr.save(i, state.state_dict(), async_save=True)
            if pos == block:
                mgr.wait()                      # drain inside the gap
            wall = time.perf_counter() - t0
            if not is_gap:
                (ckpt_walls if in_save_block else base_walls).append(wall)
            if i >= target:
                break
        else:
            continue
        break

    base = float(np.median(base_walls)) * args.overhead_steps
    ckpt = float(np.median(ckpt_walls)) * args.overhead_steps
    pct = (ckpt - base) / base * 100.0
    return {"overhead_steps": args.overhead_steps,
            "overhead_save_every": save_every,
            "overhead_baseline_s": round(base, 3),
            "overhead_async_save_s": round(ckpt, 3),
            "overhead_pct": round(pct, 1),
            "overhead_ok": args.overhead_max_pct is None
            or pct <= args.overhead_max_pct}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--save-every", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--seq-len", type=int, default=16)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--n-samples", type=int, default=64)
    ap.add_argument("--ckpt-root", default=None,
                    help="checkpoint scratch dir (default: a tempdir)")
    ap.add_argument("--timeline-dir", default=None,
                    help="goodput timeline segment dir (default: under "
                         "the checkpoint scratch dir — pass a path to "
                         "keep the segments for tools/goodput_report.py)")
    ap.add_argument("--sweep", type=int, default=0, metavar="N",
                    help="run N seeded scenarios (seed..seed+N-1); the "
                         "slow tier's mode")
    ap.add_argument("--overhead", action="store_true",
                    help="also measure async-save overhead vs no "
                         "checkpointing")
    ap.add_argument("--overhead-steps", type=int, default=8)
    ap.add_argument("--overhead-trials", type=int, default=3,
                    help="sample-count multiplier for the paired blocks")
    ap.add_argument("--overhead-save-every", type=int, default=5,
                    help="save cadence for the overhead measurement "
                         "(separate from the chaos scenarios' "
                         "--save-every: the overlap claim is about a "
                         "production-shaped cadence, while the chaos "
                         "oracle deliberately saves absurdly often)")
    ap.add_argument("--overhead-max-pct", type=float, default=None,
                    help="fail if async-save overhead exceeds this pct")
    ap.add_argument("--quick", action="store_true",
                    help="tier-1 budget mode: one scenario, tiniest model")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.quick:
        args.steps = min(args.steps, 8)
        args.hidden = 32
        args.n_samples = 32
    tmp = None
    if args.ckpt_root is None:
        tmp = tempfile.mkdtemp(prefix="chaos_train_")
        args.ckpt_root = tmp
    if args.timeline_dir is None:
        args.timeline_dir = os.path.join(args.ckpt_root, "timeline")

    try:
        seeds = range(args.seed, args.seed + max(1, args.sweep))
        rows = [run_scenario(s, args) for s in seeds]
        result = {"scenarios": rows, "ok": all(r["ok"] for r in rows)}
        if args.overhead:
            result.update(run_overhead(args.seed, args))
            result["ok"] = result["ok"] and result["overhead_ok"]

        if args.json:
            print(json.dumps(result, indent=2, default=str))
        else:
            for r in rows:
                status = "OK " if r["ok"] else "FAIL"
                print(f"chaos_train [{status}] seed={r['seed']} "
                      f"kill@{r['kill_step']} resume@{r['resumed_at']} "
                      f"replayed={r['replayed']} steps={r['steps']} "
                      f"({r['wall_s']}s)")
                g = r.get("goodput")
                if g:
                    print(f"  goodput: {g['goodput_ratio']:.1%} of "
                          f"{g['wall_s']:.2f}s wall — restart_downtime "
                          f"{g['restart_downtime_s']:.3f}s, replay "
                          f"{g['replay_s']:.3f}s "
                          f"({g['replayed_steps']} steps)")
                for d in r["divergences"]:
                    print(f"  DIVERGENCE: {d}")
                for c in r["corrupt"]:
                    print(f"  CORRUPT: {c}")
            if args.overhead:
                print(f"chaos_train overhead: baseline "
                      f"{result['overhead_baseline_s']}s, async-save "
                      f"{result['overhead_async_save_s']}s "
                      f"({result['overhead_pct']:+.1f}%"
                      + (")" if args.overhead_max_pct is None else
                         f", max {args.overhead_max_pct}%)"))
            print("chaos_train: " + ("all scenarios bit-exact"
                                     if result["ok"] else "FAILURES"))
        return 0 if result["ok"] else 1
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    raise SystemExit(main())
