"""Swin-T B=32 step decomposition (real step deltas, vit_budget.py style).

Swin-T at B=32 measures ~11% MFU — far under the dense-model rows. This
pins WHERE the 39 ms step goes with two ablations run against the full
step in the same session:

  1. attention ablated (values-passthrough in WindowAttention, both the
     fused-bias kernel path and the XLA fallback) — isolates the window
     S=49 attention math + its kernel;
  2. window/roll plumbing ablated on top (identity _windows/_unwindows
     with the same [B*nW, N, C] output shape via reshape only) — isolates
     the partition/merge/roll layout traffic.

What remains after both is patch-embed + MLPs + LN + head + optimizer.

PYTHONPATH=/root/repo python tools/swin_budget.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tools.step_budget import timed  # noqa: E402


def build(B, ablate_attn=False, ablate_windows=False):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.vision.models import swin_t
    from paddle_tpu.vision.models import swin as SW

    orig_fwd = SW.WindowAttention.forward
    orig_win = SW.SwinBlock._windows
    orig_unwin = SW.SwinBlock._unwindows
    if ablate_attn:
        def stub_fwd(self, xw, mask, n_windows=0):
            # values passthrough: keeps qkv/proj matmuls, drops the
            # S=49 attention math + kernel
            qkv = self.qkv(xw)
            f3 = qkv.shape[-1]
            return self.proj(qkv[:, :, 2 * f3 // 3:])
        SW.WindowAttention.forward = stub_fwd
    if ablate_windows:
        def stub_win(self, x):
            from paddle_tpu.core import ops
            # same output shape, no roll / 6-D transpose: plain reshape
            return ops.reshape(x, [-1, self.ws * self.ws, x.shape[-1]])

        def stub_unwin(self, xw, b):
            from paddle_tpu.core import ops
            return ops.reshape(xw, [b, self.H * self.W, xw.shape[-1]])
        SW.SwinBlock._windows = stub_win
        SW.SwinBlock._unwindows = stub_unwin

    try:
        paddle.seed(0)
        model = swin_t(num_classes=1000)
        model.to(dtype="bfloat16")
        ce = nn.CrossEntropyLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype="bfloat16")
        step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
        iters = 8
        x = paddle.to_tensor(np.random.randn(iters, B, 3, 224, 224)
                             .astype("bfloat16"))
        y = paddle.to_tensor(np.random.randint(0, 1000, (iters, B))
                             .astype("int64"))
        ms = timed(step, iters, x, y)
    finally:
        SW.WindowAttention.forward = orig_fwd
        SW.SwinBlock._windows = orig_win
        SW.SwinBlock._unwindows = orig_unwin
    return ms


def main():
    B = int(os.environ.get("PADDLE_TPU_BENCH_B", "32"))
    full = build(B)
    noat = build(B, ablate_attn=True)
    nowin = build(B, ablate_attn=True, ablate_windows=True)
    print(f"B={B}: full {full:7.2f} ms")
    print(f"  attention term          {full - noat:6.2f} ms")
    print(f"  window/roll layout term {noat - nowin:6.2f} ms")
    print(f"  residual (embed+MLP+LN+head+optimizer) {nowin:6.2f} ms")


if __name__ == "__main__":
    main()
