"""Trace one training-step executable on TPU and print device-time tables.

Thin CLI over `paddle_tpu.profiler.trace_analysis` (where the
.trace.json.gz parser now lives): mirrors bench.py's model configs
(vit / bert / gpt / swin / resnet50), runs a few steps under
jax.profiler.trace, then prints the KernelView / DistributedView tables —
the only trustworthy per-component timing on remote-dispatch runtimes
(host-side timers measure dispatch, not device work).

Usage: python tools/profile_step.py vit [outdir]
"""
import os
import sys

sys.path.insert(0, ".")


def build_step(which):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    import paddle_tpu.nn as nn

    if which == "vit":
        from paddle_tpu.models import VisionTransformer, vit_config
        cfg = vit_config("vit-l16", image_size=224, num_classes=1000)
        paddle.seed(0)
        model = VisionTransformer(cfg)
        model.to(dtype="bfloat16")
        ce = nn.CrossEntropyLoss()
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype="bfloat16")
        step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
        B = int(os.environ.get("PADDLE_TPU_BENCH_B", "32"))
        x = paddle.to_tensor(np.random.randn(4, B, 3, 224, 224)
                             .astype("bfloat16"))
        y = paddle.to_tensor(np.random.randint(0, 1000, (4, B))
                             .astype("int64"))
        return step, (x, y)
    if which == "bert":
        from paddle_tpu.models import BertForMaskedLM, bert_config
        cfg = bert_config("bert-base")
        paddle.seed(0)
        model = BertForMaskedLM(cfg)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype="bfloat16")
        step = TrainStep(model, opt,
                         lambda ids, lbl: model.loss(ids, lbl,
                                                     chunk_size=256))
        B = int(os.environ.get("PADDLE_TPU_BENCH_B", "32"))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, B, 512))
                               .astype("int32"))
        lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, B, 512))
                               .astype("int64"))
        return step, (ids, lbl)
    if which == "gpt":
        from paddle_tpu.models import GPTForCausalLM, gpt_config
        preset = os.environ.get("PADDLE_TPU_BENCH_PRESET", "gpt3-1.3b")
        B = int(os.environ.get("PADDLE_TPU_BENCH_B", "3"))
        S = int(os.environ.get("PADDLE_TPU_BENCH_S", "2048"))
        cfg = gpt_config(preset, max_position_embeddings=max(1024, S))
        paddle.seed(0)
        model = GPTForCausalLM(cfg)
        model.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters(),
                                     moment_dtype="bfloat16")
        step = TrainStep(model, opt,
                         lambda a, b: model.loss(a, b, chunk_size=512))
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (4, B, S))
                               .astype("int32"))
        return step, (ids, ids)
    if which in ("swin", "resnet50"):
        # shared imagenet-train harness; only constructor/opt/batch differ
        paddle.seed(0)
        if which == "swin":
            from paddle_tpu.vision.models import swin_t
            model, default_b = swin_t(num_classes=1000), 32
            opt_fn = lambda ps: paddle.optimizer.AdamW(  # noqa: E731
                learning_rate=1e-4, parameters=ps, moment_dtype="bfloat16")
        else:
            from paddle_tpu.vision.models import resnet50
            model, default_b = resnet50(num_classes=1000), 64
            opt_fn = lambda ps: paddle.optimizer.Momentum(  # noqa: E731
                learning_rate=0.1, parameters=ps)
        model.to(dtype="bfloat16")
        ce = nn.CrossEntropyLoss()
        opt = opt_fn(model.parameters())
        step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
        B = int(os.environ.get("PADDLE_TPU_BENCH_B", str(default_b)))
        x = paddle.to_tensor(np.random.randn(4, B, 3, 224, 224)
                             .astype("bfloat16"))
        y = paddle.to_tensor(np.random.randint(0, 1000, (4, B))
                             .astype("int64"))
        return step, (x, y)
    raise SystemExit(f"unknown model {which}")


def aggregate(outdir, n_steps):
    """Parse + print the capture via profiler.trace_analysis."""
    from paddle_tpu.profiler import trace_analysis as ta
    path = ta.find_trace_file(outdir)
    if path is None:
        raise SystemExit(f"no trace files under {outdir}")
    an = ta.analyze(path, steps=n_steps)
    print(f"\ntrace: {path}")
    print(an.kernel_view())
    print()
    print(an.distributed_view())
    rows = [(r["name"], r["dur_us"]) for r in an.op_totals()]
    return rows, an.total_device_us()


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "vit"
    outdir = sys.argv[2] if len(sys.argv) > 2 else f"/tmp/trace_{which}"
    import jax
    step, args = build_step(which)
    losses = step.run_steps(4, *args)          # compile + warm
    _ = float(losses.numpy()[-1])
    n = 4
    jax.profiler.start_trace(outdir)
    losses = step.run_steps(n, *args)
    _ = float(losses.numpy()[-1])
    jax.profiler.stop_trace()
    aggregate(outdir, n)


if __name__ == "__main__":
    main()
