"""ViT-L B=32 vs B=64 attention-term ablation (real step deltas).

The B=32 operating point sits ~10 MFU points under B=64 with the same
kernel. This measures WHERE: run the full step and a variant with the
fused-MHA call replaced by a values-passthrough (keeps qkv/out projections
and everything else; ablates only the S^2 attention math + its kernel),
at both batch sizes. If the non-attention time scales ~2x from B=32 to
B=64 but the attention term does not, the kernel's batch-pipelining is
the pinned cost.

PYTHONPATH=/root/repo:$PYTHONPATH python tools/vit_budget.py
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from tools.step_budget import timed  # noqa: E402


def build(B, ablate_attn):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import VisionTransformer, vit_config
    from paddle_tpu.ops.pallas import fused_mha as FM

    if ablate_attn:
        orig = FM.fused_mha

        def stub(qkv, num_heads, **kw):
            f3 = qkv.shape[-1]
            return qkv[..., 2 * f3 // 3:]          # values passthrough
        FM.fused_mha = stub
    cfg = vit_config("vit-l16", image_size=224, num_classes=1000)
    paddle.seed(0)
    model = VisionTransformer(cfg)
    model.to(dtype="bfloat16")
    ce = nn.CrossEntropyLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 moment_dtype="bfloat16")
    step = TrainStep(model, opt, lambda x, y: ce(model(x), y))
    iters = 8
    x = paddle.to_tensor(np.random.randn(iters, B, 3, 224, 224)
                         .astype("bfloat16"))
    y = paddle.to_tensor(np.random.randint(0, 1000, (iters, B))
                         .astype("int64"))
    ms = timed(step, iters, x, y)
    if ablate_attn:
        FM.fused_mha = orig
    return ms


def main():
    for B in (32, 64):
        full = build(B, False)
        noat = build(B, True)
        print(f"B={B}: full {full:7.2f} ms  no-attn {noat:7.2f} ms  "
              f"attention term {full - noat:6.2f} ms "
              f"({(full - noat) / B * 1e3:.1f} us/img)")


if __name__ == "__main__":
    main()
