"""Hardware validation for fused_mha_bias at swin stage shapes.

PYTHONPATH=/root/repo:$PYTHONPATH python tools/validate_mha_bias_tpu.py
"""
import os
import sys

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from paddle_tpu.ops.pallas.fused_mha_bias import fused_mha_bias  # noqa
from tests.test_fused_mha_bias import _ref_bias  # noqa


def check(b, s, nh, hd, r_n, g=None, seed=0, tag=""):
    rng = np.random.RandomState(seed)
    qkv = jnp.asarray(rng.randn(b, s, 3 * nh * hd).astype(np.float32) * 0.3,
                      jnp.bfloat16)
    bias = jnp.asarray(rng.randn(r_n, nh, s, s).astype(np.float32) * 0.5)

    def fk(a, bb):
        return jnp.sum(fused_mha_bias(a, nh, bb, heads_per_program=g)
                       .astype(jnp.float32) ** 2)

    def fr(a, bb):
        return jnp.sum(_ref_bias(a, nh, bb).astype(jnp.float32) ** 2)

    vk, gk = jax.value_and_grad(fk, argnums=(0, 1))(qkv, bias)
    vr, gr = jax.value_and_grad(fr, argnums=(0, 1))(qkv, bias)
    rel = abs(float(vk) - float(vr)) / (abs(float(vr)) + 1e-9)
    dq = np.abs(np.asarray(gk[0], np.float32)
                - np.asarray(gr[0], np.float32)).max()
    db = np.abs(np.asarray(gk[1], np.float32)
                - np.asarray(gr[1], np.float32)).max()
    dbs = np.abs(np.asarray(gr[1], np.float32)).max() + 1e-9
    print(f"{tag}: fwd-rel {rel:.2e}  dqkv-maxdiff {dq:.3e}  "
          f"dbias-relmax {db / dbs:.3e}")


if __name__ == "__main__":
    # swin-t stages: (windows grouped) S=196, heads 3/6/12/24, hd=32
    check(64, 196, 3, 32, 16, g=3, tag="stage1 G=nh=3")
    check(16, 196, 6, 32, 4, g=6, tag="stage2 G=nh=6")
    check(4, 196, 12, 32, 1, g=4, tag="stage3 G=4")
    check(8, 196, 24, 32, 1, g=4, tag="stage4 G=4")
    check(8, 392, 4, 32, 2, g=4, tag="wg8 S=392 G=4")
