#!/usr/bin/env python
"""Telemetry ops-surface smoke (ISSUE 12) — the tier-1 gate for the obs
layer: boot a toy ServingEngine, attach the TelemetryServer, and prove
the whole surface end-to-end:

  1. all four endpoints (/metrics /healthz /statusz /tracez) answer
     CONCURRENTLY with live decode — a scraper thread hammers them for
     the duration of the measured traffic, validating every payload
     (promtool-style exposition lint on /metrics, JSON parse + required
     keys elsewhere);
  2. zero post-warmup jit cache misses with the server attached (a
     scrape must never trigger a compile — the handlers only read
     host-side telemetry state);
  3. measured throughput overhead of the live server vs server-off,
     PAIRED INTERLEAVED blocks with per-batch medians (the r12 chaos
     estimator: whole-leg walls on a shared box swing with neighbor
     load). The ISSUE bar is <1% — physically plausible since the
     serving thread only gains ~3 clock reads + tuple appends per chunk
     — but this box's scheduler noise is several percent, so the CI
     gate defaults to a 10% catastrophic-regression backstop
     (--overhead-max-pct 1 on an unloaded host is the tight-bar run);
  4. the drain handshake: begin_drain() flips /healthz to 503/draining;
  5. SLO burn-rate monitors stay SILENT over the clean run (alert
     firing under injected latency is tests/test_obs.py's job).

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/obs_smoke.py [--pairs 3] [--batches 4]
        [--overhead-max-pct 10] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


class Scraper(threading.Thread):
    """GET + validate all four endpoints in a loop while `active` is
    set; pause (server idle) while it is clear — the paired overhead
    estimator toggles it per block."""

    def __init__(self, srv, interval: float = 0.1):
        super().__init__(name="obs-smoke-scraper", daemon=True)
        self.srv = srv
        self.interval = interval     # 10 Hz default — ~100x faster than
        #                              a production Prometheus cadence; a
        #                              delay-free busy loop would measure
        #                              GIL starvation, not telemetry cost
        self.stop = threading.Event()
        self.active = threading.Event()
        self.scrapes = 0
        self.errors = []

    def _one_pass(self):
        from urllib.request import urlopen
        from paddle_tpu.obs import lint_exposition
        text = urlopen(self.srv.url("/metrics"), timeout=5).read().decode()
        lint_exposition(text)                  # promtool-style conformance
        h = json.loads(urlopen(self.srv.url("/healthz"),
                               timeout=5).read())
        for key in ("status", "draining", "queue_depth",
                    "overloaded_total"):
            if key not in h:
                raise AssertionError(f"/healthz missing {key}")
        s = json.loads(urlopen(self.srv.url("/statusz"), timeout=5).read())
        for key in ("engine", "config", "compile", "counters"):
            if key not in s:
                raise AssertionError(f"/statusz missing {key}")
        t = json.loads(urlopen(self.srv.url("/tracez?limit=8"),
                               timeout=5).read())
        if "summary" not in t or "traces" not in t:
            raise AssertionError("/tracez missing summary/traces")

    def run(self):
        while not self.stop.is_set():
            if not self.active.wait(timeout=0.05):
                continue
            try:
                self._one_pass()
                self.scrapes += 1
            except Exception as e:             # noqa: BLE001 — the gate
                self.errors.append(f"{type(e).__name__}: {e}")
                return
            if self.stop.wait(timeout=self.interval):
                return


def run_block(engine, prompts, batches):
    """One measured block: `batches` full micro-batches, closed-loop.
    Returns per-batch walls (the paired estimator's samples)."""
    walls = []
    B = engine.config.max_batch
    for b in range(batches):
        t0 = time.perf_counter()
        for i in range(B):
            engine.submit(prompts[(b * B + i) % len(prompts)])
        engine.drain()
        walls.append(time.perf_counter() - t0)
    return walls


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--pairs", type=int, default=3,
                    help="interleaved (server-off | server-on) block "
                         "pairs for the overhead estimate")
    ap.add_argument("--batches", type=int, default=10,
                    help="micro-batches per block")
    ap.add_argument("--scrape-interval", type=float, default=0.1,
                    help="seconds between full endpoint passes while "
                         "the ON leg runs (0.1 = 10 Hz, already ~100x a "
                         "production Prometheus cadence)")
    ap.add_argument("--overhead-max-pct", type=float, default=10.0,
                    help="CI backstop on the measured throughput "
                         "overhead (the paper bar is 1%% on an unloaded "
                         "host)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.obs import SLOMonitor

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128)
    model = GPTForCausalLM(cfg)
    model.eval()
    engine = ServingEngine(model, ServingConfig(
        max_batch=2, prompt_cap=12, max_new_tokens=8, decode_chunk=4))
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, cfg.vocab_size,
                           (int(rng.randint(3, 13)),)).astype(np.int64)
               for _ in range(16)]

    # warmup: the full {prefill + chunk-depth} executable set
    for p in prompts[:2]:
        engine.submit(p)
    engine.drain()

    failures = []
    miss0 = compile_cache_misses()

    srv = engine.serve_telemetry()
    slo = SLOMonitor("ttft_p99=30s,e2e_p99=60s,goodput=0.5",
                     engine.metrics, long_s=60.0, short_s=5.0,
                     burn_threshold=1.0)
    srv.registry.register("slo", slo.metrics_text)
    scraper = Scraper(srv, interval=args.scrape_interval)
    scraper.start()

    # paired interleaved blocks: OFF = server bound but idle (no scrape
    # traffic), ON = the scraper hammering all four endpoints while the
    # same batches decode. Interleaving cancels the box's slow drift;
    # per-batch medians cancel its spikes.
    off_walls, on_walls = [], []
    try:
        for _ in range(max(args.pairs, 1)):
            scraper.active.clear()
            off_walls += run_block(engine, prompts, args.batches)
            scraper.active.set()
            on_walls += run_block(engine, prompts, args.batches)
            slo.poll()
    finally:
        scraper.stop.set()
        scraper.join(timeout=5)

    if scraper.errors:
        failures.append(f"endpoint validation failed: "
                        f"{scraper.errors[0]}")
    if scraper.scrapes < 1:
        failures.append("scraper completed zero full passes")

    dm = compile_cache_misses() - miss0
    if dm:
        failures.append(f"{dm} jit cache misses post-warmup with the "
                        f"server attached (must be 0)")
    if slo.breaching or slo.alerts_total:
        failures.append(f"SLO monitor fired {slo.alerts_total} alerts "
                        f"on the clean run (must stay silent)")

    # the drain handshake
    from urllib.error import HTTPError
    from urllib.request import urlopen
    engine.begin_drain()
    try:
        urlopen(srv.url("/healthz"), timeout=5)
        failures.append("/healthz returned 200 while draining "
                        "(must be 503)")
    except HTTPError as e:
        body = json.loads(e.read())
        if e.code != 503 or body.get("status") != "draining":
            failures.append(f"/healthz drain response wrong: "
                            f"{e.code} {body}")
    engine.resume_admission()
    srv.close()

    med_off, med_on = _median(off_walls), _median(on_walls)
    overhead_pct = (med_on - med_off) / med_off * 100.0
    if overhead_pct > args.overhead_max_pct:
        failures.append(f"telemetry overhead {overhead_pct:.1f}% over "
                        f"the {args.overhead_max_pct:.1f}% backstop")

    out = {"scrapes": scraper.scrapes,
           "batches_per_leg": len(off_walls),
           "median_batch_wall_off_ms": round(med_off * 1e3, 2),
           "median_batch_wall_on_ms": round(med_on * 1e3, 2),
           "overhead_pct": round(overhead_pct, 2),
           "overhead_max_pct": args.overhead_max_pct,
           "post_warmup_jit_misses": dm,
           "slo_alerts": slo.alerts_total,
           "traces_retained": engine.metrics.trace_buffer.summary()[
               "retained"],
           "ok": not failures, "failures": failures}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"obs_smoke: {scraper.scrapes} full endpoint passes while "
              f"serving; median batch wall {out['median_batch_wall_off_ms']}"
              f"ms off / {out['median_batch_wall_on_ms']}ms on "
              f"-> overhead {out['overhead_pct']}% "
              f"(backstop {args.overhead_max_pct}%)")
        print(f"obs_smoke: post-warmup jit misses {dm}, SLO alerts "
              f"{slo.alerts_total}, {out['traces_retained']} traces "
              f"retained, drain handshake ok")
    for f in failures:
        print(f"obs_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("obs_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
