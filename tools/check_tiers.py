#!/usr/bin/env python
"""Tier-budget checker — keep the tier-1 suite inside its wall-time cap.

The tier-1 wrapper runs ``pytest -m 'not slow'`` under a hard timeout
(ROADMAP.md: 870 s). Tests drift slower over PRs; when one quietly crosses
the line the whole tier starts truncating and DOTS_PASSED collapses. This
tool enforces the tier contract from MEASURED durations:

  1. every test whose recorded wall time exceeds --slow-threshold must
     carry the ``slow`` marker (it does not belong in tier-1), and
  2. the summed duration of all non-slow tests must stay under --budget.

Durations come from JSONL files the test harness records when
``PADDLE_TPU_TIER_DURATIONS=<path>`` is set (see tests/conftest.py):
one ``{"nodeid", "duration", "markers", "outcome"}`` row per test call.
Multiple files merge (max duration per nodeid — the safe estimate across
runs). ``tools/run_tier1.sh`` wires recording + checking around the
canonical tier-1 command.

    python tools/check_tiers.py /tmp/tier_durations.jsonl \
        [--budget 780] [--slow-threshold 60] [--json]

Exit status: 0 = contract holds, 1 = violations, 2 = no usable records.
"""
from __future__ import annotations

import argparse
import json
import sys


def load_records(paths):
    """Merge duration rows: max duration per nodeid, union of markers."""
    recs = {}
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                nid = row.get("nodeid")
                if not nid or "duration" not in row:
                    continue
                cur = recs.get(nid)
                if cur is None or row["duration"] > cur["duration"]:
                    markers = set(row.get("markers") or [])
                    if cur:
                        markers |= set(cur.get("markers") or [])
                    recs[nid] = {"nodeid": nid,
                                 "duration": float(row["duration"]),
                                 "markers": sorted(markers),
                                 "outcome": row.get("outcome")}
                else:
                    cur["markers"] = sorted(
                        set(cur.get("markers") or [])
                        | set(row.get("markers") or []))
    return list(recs.values())


def check(records, *, budget: float, slow_threshold: float,
          lint_seconds: float = None, lint_budget: float = 15.0,
          chaos_seconds: float = None,
          chaos_budget: float = 120.0,
          goodput_seconds: float = None,
          goodput_budget: float = 30.0,
          obs_seconds: float = None,
          obs_budget: float = 60.0,
          fleet_seconds: float = None,
          fleet_budget: float = 60.0,
          fleet_chaos_seconds: float = None,
          fleet_chaos_budget: float = 60.0,
          shardlint_seconds: float = None,
          shardlint_budget: float = 60.0,
          sharded_serve_seconds: float = None,
          sharded_serve_budget: float = 90.0,
          flightrec_seconds: float = None,
          flightrec_budget: float = 60.0,
          memz_seconds: float = None,
          memz_budget: float = 60.0,
          probe_seconds: float = None,
          probe_budget: float = 90.0,
          comm_seconds: float = None,
          comm_budget: float = 180.0) -> dict:
    unmarked_slow = []       # should carry `slow` but don't
    tier1 = []               # everything tier-1 actually collects
    for r in records:
        marks = set(r["markers"])
        if "slow" in marks:
            continue
        tier1.append(r)
        if r["duration"] > slow_threshold:
            unmarked_slow.append(r)
    tier1_total = sum(r["duration"] for r in tier1)
    # the lint budget line: tools/lint_source.py runs inside the tier-1
    # wrapper and must stay trivial (default cap 15s) — a lint pass that
    # grows into real wall time belongs in its own tier, not ahead of
    # every tier-1 run
    lint_over = (lint_seconds is not None
                 and lint_seconds > lint_budget)
    # the chaos budget line: tools/chaos_train.py --quick runs inside the
    # tier-1 wrapper (ISSUE 7) — one seeded kill/resume scenario + the
    # async-save overhead report must stay well under the tier cap; the
    # multi-seed sweep belongs to the slow tier
    chaos_over = (chaos_seconds is not None
                  and chaos_seconds > chaos_budget)
    # the goodput budget line: tools/goodput_report.py stitches the chaos
    # leg's timeline segments inside the tier-1 wrapper (ISSUE 8) — a
    # pure-host JSONL parse that must stay trivial next to the suite
    goodput_over = (goodput_seconds is not None
                    and goodput_seconds > goodput_budget)
    # the obs budget line: tools/obs_smoke.py boots a toy engine + the
    # telemetry server inside the tier-1 wrapper (ISSUE 12) — four
    # endpoint validations plus the paired overhead estimate must stay a
    # small fraction of the tier cap
    obs_over = (obs_seconds is not None and obs_seconds > obs_budget)
    # the fleet budget line: tools/fleet_smoke.py aggregates three toy
    # replicas inside the tier-1 wrapper (ISSUE 13) — merge + kill-one
    # + oracle checks must stay a small fraction of the tier cap
    fleet_over = (fleet_seconds is not None
                  and fleet_seconds > fleet_budget)
    # the fleet-chaos budget line: tools/fleet_chaos_smoke.py drives a
    # seeded replica kill through the FleetRouter inside the tier-1
    # wrapper (ISSUE 14) — failover + spill round-trip + oracle parity
    # must stay a small fraction of the tier cap
    fleet_chaos_over = (fleet_chaos_seconds is not None
                        and fleet_chaos_seconds > fleet_chaos_budget)
    # the shardlint budget line: tools/graph_lint.py's sharded targets
    # (train-step-dp/tp + comm-xcheck) compile TrainStep(gpt) twice on
    # the 8-device host mesh inside the tier-1 wrapper (ISSUE 15) — two
    # toy XLA compiles plus a fixture parse must stay a small fraction
    # of the tier cap
    shardlint_over = (shardlint_seconds is not None
                      and shardlint_seconds > shardlint_budget)
    # the sharded-serve budget line: tools/graph_lint.py's
    # gpt-paged-sharded target proves the multi-chip serving CommPlan
    # (ISSUE 16) — one 4-shard toy engine's executable set audited on
    # the host mesh must stay a small fraction of the tier cap
    sharded_serve_over = (sharded_serve_seconds is not None
                         and sharded_serve_seconds > sharded_serve_budget)
    # the flightrec budget line: tools/flightrec_smoke.py boots a toy
    # engine with the flight recorder attached (ISSUE 17) — the injected
    # SLO breach, one /profilez round-trip and two perf_diff subprocess
    # gates must stay a small fraction of the tier cap
    flightrec_over = (flightrec_seconds is not None
                      and flightrec_seconds > flightrec_budget)
    # the memz budget line: tools/memz_smoke.py boots a toy paged engine
    # with the HBM ledger attached (ISSUE 18) — conservation under
    # churn, the concurrent /memz scrape, one injected OOM post-mortem
    # and a mem-pressure episode must stay a small fraction of the cap
    memz_over = (memz_seconds is not None
                 and memz_seconds > memz_budget)
    # the probe budget line: tools/probe_smoke.py drives golden-canary
    # probers at 2 Hz over a 3-replica toy fleet inside the tier-1
    # wrapper (ISSUE 19) — the clean interleaved leg, one corrupted KV
    # block's detection/ejection and the fleet-page checks must stay a
    # small fraction of the tier cap
    probe_over = (probe_seconds is not None
                  and probe_seconds > probe_budget)
    # the comm budget line: tools/comm_smoke.py spawns two worker
    # processes, each compiling a toy-GPT int8-gradient-sync TrainStep
    # on a 2-device CPU mesh, twice per worker with a state-restore
    # replay in between (ISSUE 20) — two toy XLA compiles per worker
    # plus the CommPlan audit must stay a small fraction of the cap
    comm_over = (comm_seconds is not None
                 and comm_seconds > comm_budget)
    return {
        "n_records": len(records),
        "n_tier1": len(tier1),
        "tier1_total_s": round(tier1_total, 1),
        "budget_s": budget,
        "over_budget": tier1_total > budget,
        "slow_threshold_s": slow_threshold,
        "lint_seconds": lint_seconds,
        "lint_budget_s": lint_budget,
        "lint_over_budget": lint_over,
        "chaos_seconds": chaos_seconds,
        "chaos_budget_s": chaos_budget,
        "chaos_over_budget": chaos_over,
        "goodput_seconds": goodput_seconds,
        "goodput_budget_s": goodput_budget,
        "goodput_over_budget": goodput_over,
        "obs_seconds": obs_seconds,
        "obs_budget_s": obs_budget,
        "obs_over_budget": obs_over,
        "fleet_seconds": fleet_seconds,
        "fleet_budget_s": fleet_budget,
        "fleet_over_budget": fleet_over,
        "fleet_chaos_seconds": fleet_chaos_seconds,
        "fleet_chaos_budget_s": fleet_chaos_budget,
        "fleet_chaos_over_budget": fleet_chaos_over,
        "shardlint_seconds": shardlint_seconds,
        "shardlint_budget_s": shardlint_budget,
        "shardlint_over_budget": shardlint_over,
        "sharded_serve_seconds": sharded_serve_seconds,
        "sharded_serve_budget_s": sharded_serve_budget,
        "sharded_serve_over_budget": sharded_serve_over,
        "flightrec_seconds": flightrec_seconds,
        "flightrec_budget_s": flightrec_budget,
        "flightrec_over_budget": flightrec_over,
        "memz_seconds": memz_seconds,
        "memz_budget_s": memz_budget,
        "memz_over_budget": memz_over,
        "probe_seconds": probe_seconds,
        "probe_budget_s": probe_budget,
        "probe_over_budget": probe_over,
        "comm_seconds": comm_seconds,
        "comm_budget_s": comm_budget,
        "comm_over_budget": comm_over,
        "unmarked_slow": sorted(unmarked_slow,
                                key=lambda r: -r["duration"]),
        "slowest_tier1": sorted(tier1, key=lambda r: -r["duration"])[:10],
        "ok": (tier1_total <= budget and not unmarked_slow
               and not lint_over and not chaos_over and not goodput_over
               and not obs_over and not fleet_over
               and not fleet_chaos_over and not shardlint_over
               and not sharded_serve_over and not flightrec_over
               and not memz_over and not probe_over
               and not comm_over),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("durations", nargs="+",
                    help="JSONL duration files (PADDLE_TPU_TIER_DURATIONS)")
    ap.add_argument("--budget", type=float, default=780.0,
                    help="max summed seconds for non-slow tests "
                         "(default 780 = 90%% of the 870s tier-1 cap)")
    ap.add_argument("--slow-threshold", type=float, default=60.0,
                    help="a single test over this must be marked slow")
    ap.add_argument("--lint-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 source-lint "
                         "pass (tools/run_tier1.sh records it)")
    ap.add_argument("--lint-budget", type=float, default=15.0,
                    help="max seconds the lint pass may take on tier-1")
    ap.add_argument("--chaos-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 chaos_train "
                         "gate (tools/run_tier1.sh records it)")
    ap.add_argument("--chaos-budget", type=float, default=120.0,
                    help="max seconds the chaos gate may take on tier-1")
    ap.add_argument("--goodput-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 goodput_report "
                         "smoke (tools/run_tier1.sh records it)")
    ap.add_argument("--goodput-budget", type=float, default=30.0,
                    help="max seconds the goodput smoke may take on "
                         "tier-1")
    ap.add_argument("--obs-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 obs_smoke "
                         "leg (tools/run_tier1.sh records it)")
    ap.add_argument("--obs-budget", type=float, default=60.0,
                    help="max seconds the obs smoke may take on tier-1")
    ap.add_argument("--fleet-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 fleet_smoke "
                         "leg (tools/run_tier1.sh records it)")
    ap.add_argument("--fleet-budget", type=float, default=60.0,
                    help="max seconds the fleet smoke may take on tier-1")
    ap.add_argument("--fleet-chaos-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 "
                         "fleet_chaos_smoke leg (tools/run_tier1.sh "
                         "records it)")
    ap.add_argument("--fleet-chaos-budget", type=float, default=60.0,
                    help="max seconds the fleet chaos smoke may take "
                         "on tier-1")
    ap.add_argument("--shardlint-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 sharded "
                         "graph-lint smoke (tools/run_tier1.sh records "
                         "it)")
    ap.add_argument("--shardlint-budget", type=float, default=60.0,
                    help="max seconds the sharded graph-lint smoke may "
                         "take on tier-1 (8-device CPU mesh)")
    ap.add_argument("--sharded-serve-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 sharded "
                         "serving lint leg (tools/run_tier1.sh records "
                         "it)")
    ap.add_argument("--sharded-serve-budget", type=float, default=90.0,
                    help="max seconds the sharded serving lint leg may "
                         "take on tier-1 (4-shard toy engine on the "
                         "host mesh)")
    ap.add_argument("--flightrec-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 flight-"
                         "recorder smoke (tools/run_tier1.sh records "
                         "it)")
    ap.add_argument("--flightrec-budget", type=float, default=60.0,
                    help="max seconds the flight-recorder smoke may "
                         "take on tier-1")
    ap.add_argument("--memz-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 HBM-ledger "
                         "smoke (tools/run_tier1.sh records it)")
    ap.add_argument("--memz-budget", type=float, default=60.0,
                    help="max seconds the HBM-ledger smoke may take "
                         "on tier-1")
    ap.add_argument("--probe-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 active-"
                         "probing smoke (tools/run_tier1.sh records "
                         "it)")
    ap.add_argument("--probe-budget", type=float, default=90.0,
                    help="max seconds the active-probing smoke may "
                         "take on tier-1")
    ap.add_argument("--comm-seconds", type=float, default=None,
                    help="measured wall time of the tier-1 quantized-"
                         "gradient-sync smoke (tools/run_tier1.sh "
                         "records it)")
    ap.add_argument("--comm-budget", type=float, default=180.0,
                    help="max seconds the comm smoke may take on "
                         "tier-1 (two 2-device worker processes)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    records = load_records(args.durations)
    if not records:
        print("check_tiers: no duration records found", file=sys.stderr)
        return 2
    result = check(records, budget=args.budget,
                   slow_threshold=args.slow_threshold,
                   lint_seconds=args.lint_seconds,
                   lint_budget=args.lint_budget,
                   chaos_seconds=args.chaos_seconds,
                   chaos_budget=args.chaos_budget,
                   goodput_seconds=args.goodput_seconds,
                   goodput_budget=args.goodput_budget,
                   obs_seconds=args.obs_seconds,
                   obs_budget=args.obs_budget,
                   fleet_seconds=args.fleet_seconds,
                   fleet_budget=args.fleet_budget,
                   fleet_chaos_seconds=args.fleet_chaos_seconds,
                   fleet_chaos_budget=args.fleet_chaos_budget,
                   shardlint_seconds=args.shardlint_seconds,
                   shardlint_budget=args.shardlint_budget,
                   sharded_serve_seconds=args.sharded_serve_seconds,
                   sharded_serve_budget=args.sharded_serve_budget,
                   flightrec_seconds=args.flightrec_seconds,
                   flightrec_budget=args.flightrec_budget,
                   memz_seconds=args.memz_seconds,
                   memz_budget=args.memz_budget,
                   probe_seconds=args.probe_seconds,
                   probe_budget=args.probe_budget,
                   comm_seconds=args.comm_seconds,
                   comm_budget=args.comm_budget)

    if args.json:
        print(json.dumps(result, indent=2))
    else:
        print(f"check_tiers: {result['n_tier1']} tier-1 tests, "
              f"{result['tier1_total_s']}s total "
              f"(budget {result['budget_s']}s)")
        if result["lint_seconds"] is not None:
            print(f"  lint: {result['lint_seconds']:.2f}s "
                  f"(budget {result['lint_budget_s']}s)")
        if result.get("chaos_seconds") is not None:
            print(f"  chaos: {result['chaos_seconds']:.2f}s "
                  f"(budget {result['chaos_budget_s']}s)")
        if result.get("goodput_seconds") is not None:
            print(f"  goodput: {result['goodput_seconds']:.2f}s "
                  f"(budget {result['goodput_budget_s']}s)")
        if result.get("obs_seconds") is not None:
            print(f"  obs: {result['obs_seconds']:.2f}s "
                  f"(budget {result['obs_budget_s']}s)")
        if result.get("fleet_seconds") is not None:
            print(f"  fleet: {result['fleet_seconds']:.2f}s "
                  f"(budget {result['fleet_budget_s']}s)")
        if result.get("fleet_chaos_seconds") is not None:
            print(f"  fleet-chaos: {result['fleet_chaos_seconds']:.2f}s "
                  f"(budget {result['fleet_chaos_budget_s']}s)")
        if result.get("shardlint_seconds") is not None:
            print(f"  shardlint: {result['shardlint_seconds']:.2f}s "
                  f"(budget {result['shardlint_budget_s']}s)")
        if result.get("sharded_serve_seconds") is not None:
            print(f"  sharded-serve: "
                  f"{result['sharded_serve_seconds']:.2f}s "
                  f"(budget {result['sharded_serve_budget_s']}s)")
        if result.get("flightrec_seconds") is not None:
            print(f"  flightrec: {result['flightrec_seconds']:.2f}s "
                  f"(budget {result['flightrec_budget_s']}s)")
        if result.get("memz_seconds") is not None:
            print(f"  memz: {result['memz_seconds']:.2f}s "
                  f"(budget {result['memz_budget_s']}s)")
        if result.get("probe_seconds") is not None:
            print(f"  probe: {result['probe_seconds']:.2f}s "
                  f"(budget {result['probe_budget_s']}s)")
        if result.get("comm_seconds") is not None:
            print(f"  comm: {result['comm_seconds']:.2f}s "
                  f"(budget {result['comm_budget_s']}s)")
        if result["chaos_over_budget"]:
            print(f"  VIOLATION: chaos gate took "
                  f"{result['chaos_seconds']:.2f}s, over the "
                  f"{result['chaos_budget_s']}s chaos budget")
        if result["goodput_over_budget"]:
            print(f"  VIOLATION: goodput smoke took "
                  f"{result['goodput_seconds']:.2f}s, over the "
                  f"{result['goodput_budget_s']}s goodput budget")
        if result["obs_over_budget"]:
            print(f"  VIOLATION: obs smoke took "
                  f"{result['obs_seconds']:.2f}s, over the "
                  f"{result['obs_budget_s']}s obs budget")
        if result["fleet_over_budget"]:
            print(f"  VIOLATION: fleet smoke took "
                  f"{result['fleet_seconds']:.2f}s, over the "
                  f"{result['fleet_budget_s']}s fleet budget")
        if result["fleet_chaos_over_budget"]:
            print(f"  VIOLATION: fleet chaos smoke took "
                  f"{result['fleet_chaos_seconds']:.2f}s, over the "
                  f"{result['fleet_chaos_budget_s']}s fleet-chaos "
                  f"budget")
        if result["shardlint_over_budget"]:
            print(f"  VIOLATION: sharded graph-lint smoke took "
                  f"{result['shardlint_seconds']:.2f}s, over the "
                  f"{result['shardlint_budget_s']}s shardlint budget")
        if result["sharded_serve_over_budget"]:
            print(f"  VIOLATION: sharded serving lint leg took "
                  f"{result['sharded_serve_seconds']:.2f}s, over the "
                  f"{result['sharded_serve_budget_s']}s sharded-serve "
                  f"budget")
        if result["flightrec_over_budget"]:
            print(f"  VIOLATION: flight-recorder smoke took "
                  f"{result['flightrec_seconds']:.2f}s, over the "
                  f"{result['flightrec_budget_s']}s flightrec budget")
        if result["memz_over_budget"]:
            print(f"  VIOLATION: HBM-ledger smoke took "
                  f"{result['memz_seconds']:.2f}s, over the "
                  f"{result['memz_budget_s']}s memz budget")
        if result["probe_over_budget"]:
            print(f"  VIOLATION: active-probing smoke took "
                  f"{result['probe_seconds']:.2f}s, over the "
                  f"{result['probe_budget_s']}s probe budget")
        if result["comm_over_budget"]:
            print(f"  VIOLATION: quantized-gradient-sync smoke took "
                  f"{result['comm_seconds']:.2f}s, over the "
                  f"{result['comm_budget_s']}s comm budget")
        if result["lint_over_budget"]:
            print(f"  VIOLATION: lint pass took "
                  f"{result['lint_seconds']:.2f}s, over the "
                  f"{result['lint_budget_s']}s lint budget")
        for r in result["unmarked_slow"]:
            print(f"  VIOLATION: {r['nodeid']} took {r['duration']:.1f}s "
                  f"(> {args.slow_threshold}s) without the `slow` marker")
        if result["over_budget"]:
            print(f"  VIOLATION: non-slow total {result['tier1_total_s']}s "
                  f"exceeds budget {result['budget_s']}s — slowest:")
            for r in result["slowest_tier1"]:
                print(f"    {r['duration']:8.1f}s  {r['nodeid']}")
        if result["ok"]:
            print("  OK: tier contract holds")
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
