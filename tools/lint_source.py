#!/usr/bin/env python
"""Repo-level AST lint — ban known host-transfer hazards in hot modules.

The graph passes (tools/graph_lint.py) prove an EXECUTABLE is clean; this
lint keeps the SOURCE of the hot modules honest between audits: patterns
that concretize a possible tracer (`.item()`, `float()`/`bool()` on a
non-literal, `np.asarray(...)`) and direct `jax.device_get` in the
serving/jit layers are flagged wherever they appear, and every deliberate
host-sync site carries an inline escape naming the rule:

    tok = int(np.asarray(first.numpy())[0])   # lint: allow(tracer-asarray)

so the set of host-transfer points in the hot path is enumerable by grep.
Rules:

  tracer-item     `.item()` calls (a device->host sync, and a crash on a
                  tracer) — annotate the deliberate post-sync reads
  tracer-float    `float(x)` / `bool(x)` where x is a COMPUTED expression
  tracer-bool     (attribute/call/subscript chain — where tensor reads
                  hide; a plain name is almost always a python scalar) —
                  the implicit-transfer spellings transfer_guard catches
                  at trace time; the lint catches them at review time
  tracer-asarray  `np.asarray(...)` — fine on host data, a sync on device
                  data; annotate which one it is
  device-get      `jax.device_get(...)` in inference/ and jit/ — the hot
                  path fetches through documented sync points only

Escape: append ``# lint: allow(<rule>)`` on the statement's first line
(or the line above). Pure stdlib (ast) — runs in well under the tier-1
lint budget; findings print in the analysis table format.

    python tools/lint_source.py [--json] [--root .]
"""
from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys

# the hot modules: code that runs (or assembles) traced regions on the
# serving/training hot path. Everything else may host-sync freely.
HOT_GLOBS = (
    "paddle_tpu/models/gpt.py",
    "paddle_tpu/models/gpt_stacked.py",
    "paddle_tpu/inference/serving.py",
    "paddle_tpu/inference/kv_cache.py",
    "paddle_tpu/inference/prefix_cache.py",
    "paddle_tpu/jit/api.py",
    "paddle_tpu/jit/train_step.py",
    "paddle_tpu/ops/attention.py",
    # the checkpoint path runs INSIDE the training hot loop (async save
    # snapshots between steps): its deliberate device->host gather sites
    # (_to_host / TrainStep.state_dict — at save time syncing is the job)
    # are annotated, everything else must stay transfer-free
    "paddle_tpu/resilience/checkpoint.py",
    "paddle_tpu/resilience/state.py",
    # ISSUE 15 satellite: the newer hot modules. The fleet router runs
    # on the request path of every replica; the obs servers run threads
    # INSIDE serving processes — a stray tensor sync in a scrape handler
    # stalls the engine it observes. Deliberate host-side float()/bool()
    # reads (metrics math on already-host scalars) carry annotations.
    "paddle_tpu/inference/fleet.py",
    "paddle_tpu/obs/server.py",
    "paddle_tpu/obs/fleet.py",
)
# device-get additionally covers every file under these packages
DEVICE_GET_DIRS = ("paddle_tpu/inference", "paddle_tpu/jit")

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([a-z0-9_,\- ]+)\)")


def _allows(lines, lineno):
    """Rules allowed at `lineno` (1-based): same line or the line above."""
    out = set()
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _ALLOW_RE.search(lines[ln - 1])
            if m:
                out |= {r.strip() for r in m.group(1).split(",")}
    return out


def _is_literalish(node) -> bool:
    """Constants and simple arithmetic of constants — float(3), bool(0),
    float("1e-3") are not tracer hazards."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    if isinstance(node, ast.BinOp):
        return _is_literalish(node.left) and _is_literalish(node.right)
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, path, lines, device_get_only=False):
        self.path = path
        self.lines = lines
        self.device_get_only = device_get_only
        self.findings = []

    def _flag(self, rule, node, msg):
        if rule in _allows(self.lines, node.lineno):
            return
        self.findings.append({
            "pass": "source_lint", "code": rule, "severity": "error",
            "message": msg, "where": f"{self.path}:{node.lineno}",
            "line": self.lines[node.lineno - 1].strip()[:100]})

    def visit_Call(self, node):
        f = node.func
        # jax.device_get(...)
        if isinstance(f, ast.Attribute) and f.attr == "device_get" \
                and isinstance(f.value, ast.Name) and f.value.id == "jax":
            self._flag("device-get", node,
                       "direct jax.device_get in a hot module — fetch "
                       "through a documented sync point")
        if not self.device_get_only:
            # .item()
            if isinstance(f, ast.Attribute) and f.attr == "item":
                self._flag("tracer-item", node,
                           ".item() syncs (and crashes on a tracer) — "
                           "annotate deliberate post-sync reads")
            # float(x) / bool(x) on computed expressions (not plain
            # names/literals — those are almost always python scalars)
            if isinstance(f, ast.Name) and f.id in ("float", "bool") \
                    and len(node.args) == 1 \
                    and isinstance(node.args[0],
                                   (ast.Call, ast.Attribute,
                                    ast.Subscript, ast.Compare)):
                self._flag(f"tracer-{f.id}", node,
                           f"{f.id}() on a computed expression — "
                           f"implicit host transfer if the value is "
                           f"device-resident")
            # np.asarray(...) / numpy.asarray(...)
            if isinstance(f, ast.Attribute) and f.attr == "asarray" \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy", "_np"):
                self._flag("tracer-asarray", node,
                           "np.asarray syncs device data to host — "
                           "annotate whether the operand is host-side")
        self.generic_visit(node)


def lint_file(path, root, device_get_only=False):
    with open(os.path.join(root, path)) as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    v = _Visitor(path, src.splitlines(), device_get_only=device_get_only)
    v.visit(tree)
    return v.findings


def run(root: str):
    findings = []
    hot = set(HOT_GLOBS)
    for rel in sorted(hot):
        if os.path.exists(os.path.join(root, rel)):
            findings += lint_file(rel, root)
    for d in DEVICE_GET_DIRS:
        full = os.path.join(root, d)
        for fn in sorted(os.listdir(full)):
            rel = f"{d}/{fn}"
            if fn.endswith(".py") and rel not in hot:
                findings += lint_file(rel, root, device_get_only=True)
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    findings = run(args.root)
    if args.json:
        print(json.dumps(findings, indent=2))
    elif findings:
        print(f"lint_source: {len(findings)} violation(s)")
        for f in findings:
            print(f"  {f['where']}: [{f['code']}] {f['line']}")
            print(f"      {f['message']}")
    else:
        print("lint_source: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
