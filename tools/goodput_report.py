#!/usr/bin/env python
"""Goodput attribution CLI — render the wall-clock attribution table from
one or more timeline segments (ISSUE 8 tentpole).

Input is whatever `profiler.timeline` wrote: segment files
(`*.timeline.jsonl`), directories of them (a whole run including its
restarts), or glob patterns. Segments are stitched onto one absolute
timeline: post-restart re-runs of already-executed steps become `replay`
badput, inter-segment gaps become `restart_downtime`, and the
conservation property (categorized + idle ≡ wall within ε) is checked on
every invocation.

CI mode: `--min-goodput R` exits 1 when goodput% lands below R (and on
any conservation violation), so a training job's timeline can gate a
pipeline the same way tests do. `tools/run_tier1.sh` runs this over the
segments the chaos_train gate leaves behind.

    python tools/goodput_report.py runs/job42/            # human table
    python tools/goodput_report.py seg0.timeline.jsonl seg1.timeline.jsonl
    python tools/goodput_report.py runs/job42 --min-goodput 0.6   # CI gate
    python tools/goodput_report.py runs/job42 --prom      # /metrics dump

Exit status: 0 = ok, 1 = below --min-goodput or conservation violated,
2 = no usable segments.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("segments", nargs="+",
                    help="timeline segment files, dirs or globs")
    ap.add_argument("--min-goodput", type=float, default=None,
                    help="exit 1 if goodput ratio is below this "
                         "(0..1; CI gate)")
    ap.add_argument("--eps", type=float, default=0.05,
                    help="conservation tolerance in seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the summary dict instead of the table")
    ap.add_argument("--prom", action="store_true",
                    help="print Prometheus gauges instead of the table")
    args = ap.parse_args(argv)

    from paddle_tpu.profiler.goodput import ConservationError, GoodputReport
    from paddle_tpu.profiler.timeline import load_segments

    try:
        segs = load_segments(args.segments)
    except FileNotFoundError as e:
        print(f"goodput_report: {e}", file=sys.stderr)
        return 2
    if not segs:
        print("goodput_report: no spans in any segment", file=sys.stderr)
        return 2
    try:
        report = GoodputReport(segs, eps=args.eps)
    except ValueError as e:     # segments from different runs
        print(f"goodput_report: {e}", file=sys.stderr)
        return 2

    conservation_err = None
    try:
        report.check_conservation()
    except ConservationError as e:
        conservation_err = str(e)

    if args.json:
        out = report.summary()
        out["conservation_ok"] = conservation_err is None
        if conservation_err:
            out["conservation_error"] = conservation_err
        print(json.dumps(out, indent=2))
    elif args.prom:
        # route through the unified obs registry (ISSUE 12): the same
        # collision-checked, lint-clean composition path the telemetry
        # server scrapes — a drifting renderer fails HERE, not on the
        # dashboard. (Live jobs scrape the same gauges from a running
        # fit via hapi ProfilerCallback(telemetry=...).)
        from paddle_tpu.obs import MetricsRegistry
        reg = MetricsRegistry()
        reg.register("goodput", report.metrics_text)
        print(reg.render(), end="")
    else:
        print(report.table())

    rc = 0
    if conservation_err is not None:
        print(f"goodput_report: CONSERVATION VIOLATION: "
              f"{conservation_err}", file=sys.stderr)
        rc = 1
    gr = report.goodput_ratio
    if args.min_goodput is not None:
        if gr is None or gr < args.min_goodput:
            print(f"goodput_report: goodput "
                  f"{'n/a' if gr is None else f'{gr:.1%}'} below the "
                  f"--min-goodput {args.min_goodput:.1%} gate",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
