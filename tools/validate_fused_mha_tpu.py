"""Hardware validation for the fused short-seq MHA kernel (run on TPU).

The Mosaic PRNG has no CPU emulation, so everything dropout-related is
checked here on the real chip:
  1. compiled fwd parity vs the XLA reference (no dropout), ViT and BERT shapes
  2. compiled grad parity vs XLA autodiff of the reference
  3. dropout determinism per seed / divergence across seeds
  4. inverted-dropout mean preservation (E[out] ~ no-dropout out)
  5. drop-rate estimate from the zero fraction of a probe row
  6. finite-difference gradient consistency WITH dropout on (the backward
     regenerates the mask from the same seeds — this is the check that the
     regeneration is bit-identical)

Usage: python tools/validate_fused_mha_tpu.py
"""
import sys
import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.fused_mha import fused_mha, mha_reference_packed


def _rand_qkv(b, s, nh, hd, seed=0, dtype=np.float32):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.randn(b, s, 3 * nh * hd).astype(dtype)) * 0.3


def check(name, ok, detail=""):
    print(f"[{'PASS' if ok else 'FAIL'}] {name} {detail}")
    if not ok:
        sys.exit(1)


def main():
    dev = jax.devices()[0]
    print("device:", dev)

    # 1. forward parity, ViT-L shape (S=197 ragged) and BERT shape (S=512)
    for (s, nh, hd, tag) in [(197, 16, 64, "vit-l"), (512, 12, 64, "bert-b")]:
        qkv = _rand_qkv(2, s, nh, hd, seed=1)
        out = jax.jit(lambda a: fused_mha(a, nh))(qkv)
        want = mha_reference_packed(qkv, nh)
        err = float(jnp.max(jnp.abs(out - want)))
        check(f"fwd parity {tag}", err < 2e-4, f"max_err={err:.2e}")

    # 2. grad parity (no dropout)
    qkv = _rand_qkv(1, 197, 16, 64, seed=2)
    gk = jax.jit(jax.grad(lambda a: jnp.sum(fused_mha(a, 16) ** 2)))(qkv)
    gr = jax.grad(lambda a: jnp.sum(mha_reference_packed(a, 16) ** 2))(qkv)
    err = float(jnp.max(jnp.abs(gk - gr)))
    check("grad parity vit-l", err < 5e-3, f"max_err={err:.2e}")

    # 3. dropout determinism
    qkv = _rand_qkv(1, 512, 12, 64, seed=3)
    f = jax.jit(lambda a, sd: fused_mha(a, 12, dropout_p=0.1,
                                        dropout_seed=sd))
    a1 = np.asarray(f(qkv, 7.0))
    a2 = np.asarray(f(qkv, 7.0))
    a3 = np.asarray(f(qkv, 8.0))
    check("dropout deterministic per seed", np.array_equal(a1, a2))
    check("dropout differs across seeds", np.abs(a1 - a3).max() > 1e-6,
          f"max_delta={np.abs(a1 - a3).max():.3f}")

    # 4. mean preservation over seeds. Per-element expected sampling error
    # of an N-seed average of Bernoulli(1-p)/(1-p) masks is
    # sqrt(p/((1-p)·N)); gate at 2 sigma.
    n_seeds, p = 32, 0.1
    base = np.asarray(jax.jit(lambda a: fused_mha(a, 12))(qkv), np.float64)
    outs = [np.asarray(f(qkv, float(i)), np.float64) for i in range(n_seeds)]
    avg = np.mean(outs, axis=0)
    drift = np.abs(avg - base).mean() / (np.abs(base).mean() + 1e-9)
    bound = 2.0 * float(np.sqrt(p / ((1 - p) * n_seeds)))
    check("dropout mean preserved", drift < bound,
          f"rel_drift={drift:.4f} (2sigma bound {bound:.4f})")

    # 5. drop RATE: with q=0 the softmax is uniform (sigma=1/S), v=1 makes
    # out_i = keep_count_i / (S·(1-p)) — so mean(out)·(1-p) estimates the
    # keep rate directly. Binomial std of the estimate ~ sqrt(p(1-p)/S)/S^0.5
    s_probe, p_probe = 512, 0.3
    probe = jnp.concatenate([
        jnp.zeros((1, s_probe, 12 * 64), jnp.float32),       # q = 0
        qkv[:, :, 12 * 64:2 * 12 * 64],                      # k arbitrary
        jnp.ones((1, s_probe, 12 * 64), jnp.float32)], -1)   # v = 1
    o = np.asarray(jax.jit(lambda a: fused_mha(a, 12, dropout_p=p_probe,
                                               dropout_seed=5.0))(probe))
    keep_rate = o.mean() * (1 - p_probe)
    check("dropout rate matches p", abs(keep_rate - (1 - p_probe)) < 0.01,
          f"keep_rate={keep_rate:.4f} want {1 - p_probe:.2f}")

    # 6. backward mask regeneration consistency. Finite differences are
    # blind here (MXU default precision truncates f32 operands to bf16, so
    # the compiled function carries ~1e-3 noise). Instead: EXTRACT the
    # realized keep mask — the output is linear in v, so basis-block v
    # probes return the dropped-probability matrix pd column-block by
    # column-block, and pd == 0 exactly marks dropped entries (softmax
    # probs are strictly positive). Then compare the kernel's autodiff
    # grads against an f64 host emulation that uses the extracted mask;
    # a fwd/bwd seed mismatch would show as O(1) error in dv.
    nh, hd, s_m, p_m, seed_m = 4, 64, 128, 0.25, 3.0
    F = nh * hd
    qkv = _rand_qkv(1, s_m, nh, hd, seed=9)
    fm = jax.jit(lambda a: fused_mha(a, nh, dropout_p=p_m,
                                     dropout_seed=seed_m))
    pd = np.zeros((nh, s_m, s_m))
    for blk in range(s_m // hd):
        v_probe = np.zeros((1, s_m, F), np.float32)
        for h in range(nh):
            v_probe[0, blk * hd:(blk + 1) * hd, h * hd:(h + 1) * hd] = \
                np.eye(hd)
        probe = jnp.concatenate([qkv[:, :, :2 * F], jnp.asarray(v_probe)], -1)
        o = np.asarray(fm(probe), np.float64)
        for h in range(nh):
            pd[h][:, blk * hd:(blk + 1) * hd] = o[0, :, h * hd:(h + 1) * hd]
    keep = pd != 0.0
    drop_rate = 1.0 - keep.mean()
    check("extracted mask rate", abs(drop_rate - p_m) < 0.01,
          f"drop_rate={drop_rate:.4f}")

    # f64 host emulation with the extracted mask
    a = np.asarray(qkv, np.float64)[0]
    q_, k_, v_ = a[:, :F], a[:, F:2 * F], a[:, 2 * F:]
    w = np.random.RandomState(1).randn(s_m, F)
    gk = jax.jit(jax.grad(lambda x: jnp.sum(
        jnp.asarray(w[None], jnp.float32)
        * fused_mha(x, nh, dropout_p=p_m, dropout_seed=seed_m))))(qkv)
    gk = np.asarray(gk, np.float64)[0]
    scale = 1.0 / np.sqrt(hd)
    inv = 1.0 / (1.0 - p_m)
    ref_g = np.zeros_like(a)
    for h in range(nh):
        sl = slice(h * hd, (h + 1) * hd)
        qh, kh, vh, doh = q_[:, sl], k_[:, sl], v_[:, sl], w[:, sl]
        sc = qh @ kh.T * scale
        e = np.exp(sc - sc.max(-1, keepdims=True))
        sig = e / e.sum(-1, keepdims=True)
        m = keep[h] * inv
        pdh = sig * m
        dv = pdh.T @ doh
        dsig = (doh @ vh.T) * m
        r = (dsig * sig).sum(-1, keepdims=True)
        ds = sig * (dsig - r)
        ref_g[:, sl] = ds @ kh * scale
        ref_g[:, F + h * hd:F + (h + 1) * hd] = ds.T @ qh * scale
        ref_g[:, 2 * F + h * hd:2 * F + (h + 1) * hd] = dv
    denom = np.abs(ref_g).mean() + 1e-9
    rel = np.abs(gk - ref_g).max() / denom
    # bf16 MXU operand truncation bounds agreement at the ~1% level
    check("dropout grads match extracted-mask emulation", rel < 0.15,
          f"max_err/mean|g|={rel:.4f}")

    # 7. per-row lengths (SMEM table indexed by program id): row-exact
    # parity vs per-row masked reference, finite grads, deterministic
    # when combined with in-kernel dropout
    qkv = _rand_qkv(4, 512, 12, 64, seed=12)
    lens = jnp.asarray([512, 300, 197, 64], jnp.int32)
    out = jax.jit(lambda a, l: fused_mha(a, 12, kv_len=l))(qkv, lens)
    worst = 0.0
    for i, ln in enumerate([512, 300, 197, 64]):
        want = mha_reference_packed(qkv[i:i + 1], 12, kv_len=ln)
        worst = max(worst, float(jnp.max(jnp.abs(
            out[i:i + 1, :ln] - want[:, :ln]))))
    check("per-row lens fwd parity", worst < 2e-4, f"max_err={worst:.2e}")

    def loss_l(a, l):
        o = fused_mha(a, 12, kv_len=l)
        valid = (jnp.arange(512)[None, :, None] < l[:, None, None])
        return jnp.sum(jnp.where(valid, o, 0.0) ** 2)

    g = jax.jit(jax.grad(loss_l))(qkv, lens)
    check("per-row lens grads finite", bool(jnp.all(jnp.isfinite(g))))
    fd = jax.jit(lambda a, l: fused_mha(a, 12, kv_len=l, dropout_p=0.1,
                                        dropout_seed=3.0))
    a1, a2 = np.asarray(fd(qkv, lens)), np.asarray(fd(qkv, lens))
    check("per-row lens + dropout deterministic", np.array_equal(a1, a2))

    print("all hardware checks passed")


if __name__ == "__main__":
    main()
