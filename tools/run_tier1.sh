#!/usr/bin/env bash
# Tier-1 wrapper: the canonical ROADMAP.md tier-1 run, plus the tier-budget
# guard. Records per-test wall times (tests/conftest.py JSONL hook) and then
# runs tools/check_tiers.py so a test that outgrew the 870s cap fails the
# wrapper loudly instead of silently truncating the suite.
#
#   tools/run_tier1.sh [extra pytest args...]
#
# Exit status: the pytest status, OR the checker's when pytest passed.
set -o pipefail
cd "$(dirname "$0")/.."

# only reset the ledger when it's our scratch default — a user-provided
# PADDLE_TPU_TIER_DURATIONS accumulates across runs (check_tiers merges by
# max duration per test)
if [ -z "${PADDLE_TPU_TIER_DURATIONS:-}" ]; then
    DUR=/tmp/_tier1_durations.jsonl
    rm -f "$DUR"
else
    DUR="$PADDLE_TPU_TIER_DURATIONS"
fi
rm -f /tmp/_t1.log

# source lint first (ISSUE 6 satellite): pure-AST, fails fast on a
# banned host-transfer pattern in the hot modules. Timed so check_tiers
# can enforce the lint budget (the pass must stay trivial on tier-1).
lint_t0=$(date +%s.%N)
python tools/lint_source.py
lrc=$?
lint_secs=$(echo "$(date +%s.%N) $lint_t0" | awk '{printf "%.2f", $1-$2}')
echo "lint_source: ${lint_secs}s (exit $lrc)"

# chaos-train gate (ISSUE 7): one seeded kill/resume scenario + the
# async-save overhead report, on its own time budget. The overhead gate
# here is a catastrophic-regression backstop (25%), not the ~5% paper
# claim — this box's scheduler noise is ±5% even for the paired
# estimator; the tight-bar run is `chaos_train.py --overhead-max-pct 5`
# on an unloaded host. The multi-seed sweep is the slow tier's
# (tests/test_resilience.py::test_chaos_sweep, marked slow).
# ISSUE 8: the scenario also records goodput timeline segments (and
# asserts in-process that the kill shows up as restart_downtime+replay
# with conservation holding); the segments land in $GOODPUT_TL for the
# goodput_report smoke below.
GOODPUT_TL="${TIER1_GOODPUT_TL:-/tmp/_tier1_timeline}"
rm -rf "$GOODPUT_TL"
chaos_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_CHAOS_TIMEOUT:-300}" \
    env JAX_PLATFORMS=cpu python tools/chaos_train.py --quick --overhead \
    --overhead-max-pct "${TIER1_CHAOS_MAX_PCT:-25}" \
    --timeline-dir "$GOODPUT_TL"
chrc=$?
chaos_secs=$(echo "$(date +%s.%N) $chaos_t0" | awk '{printf "%.2f", $1-$2}')
echo "chaos_train: ${chaos_secs}s (exit $chrc)"

# goodput smoke (ISSUE 8): stitch the chaos leg's segments through the
# real CLI — the attribution table renders, conservation holds, and the
# goodput gate exercises the nonzero-exit path contract. The 0.1%
# floor is a smoke threshold (the quick chaos scenario is compile-
# dominated by design); production gates pick their own bar.
gp_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_GOODPUT_TIMEOUT:-60}" \
    env JAX_PLATFORMS=cpu python tools/goodput_report.py "$GOODPUT_TL" \
    --min-goodput "${TIER1_GOODPUT_MIN:-0.001}"
gprc=$?
goodput_secs=$(echo "$(date +%s.%N) $gp_t0" | awk '{printf "%.2f", $1-$2}')
echo "goodput_report: ${goodput_secs}s (exit $gprc)"

# obs smoke (ISSUE 12): toy engine + telemetry server, all four
# endpoints curled and validated concurrently with decode, zero
# post-warmup jit misses with the server attached, drain handshake, and
# the paired server-on/off overhead backstop (10% here — box noise; the
# <1% paper bar is `obs_smoke.py --overhead-max-pct 1` on an unloaded
# host).
obs_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_OBS_TIMEOUT:-120}" \
    env JAX_PLATFORMS=cpu python tools/obs_smoke.py \
    --overhead-max-pct "${TIER1_OBS_MAX_PCT:-10}"
obsrc=$?
obs_secs=$(echo "$(date +%s.%N) $obs_t0" | awk '{printf "%.2f", $1-$2}')
echo "obs_smoke: ${obs_secs}s (exit $obsrc)"

# fleet smoke (ISSUE 13): three in-process toy replicas aggregated by a
# FleetAggregator — merged page lint-clean under concurrent scrape +
# decode, fleet p99 vs the pooled-bucket oracle, one replica killed
# mid-run degrades to stale (never a fleet scrape 500), zero post-warmup
# jit misses across every replica.
fleet_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_FLEET_TIMEOUT:-120}" \
    env JAX_PLATFORMS=cpu python tools/fleet_smoke.py
fleetrc=$?
fleet_secs=$(echo "$(date +%s.%N) $fleet_t0" | awk '{printf "%.2f", $1-$2}')
echo "fleet_smoke: ${fleet_secs}s (exit $fleetrc)"

# fleet chaos smoke (ISSUE 14): three in-process replicas behind the
# prefix-aware FleetRouter, a seeded replica kill mid-traffic — router
# ejects + redispatches, autoscaler replaces, spill tier rehydrates,
# every output bit-identical to the fault-free oracle, zero post-warmup
# jit misses, and the prefix-vs-random routing hit-rate A/B.
fchaos_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_FLEET_CHAOS_TIMEOUT:-120}" \
    env JAX_PLATFORMS=cpu python tools/fleet_chaos_smoke.py
fchaosrc=$?
fchaos_secs=$(echo "$(date +%s.%N) $fchaos_t0" | awk '{printf "%.2f", $1-$2}')
echo "fleet_chaos_smoke: ${fchaos_secs}s (exit $fchaosrc)"

# sharded graph-lint smoke (ISSUE 15 + 20): the SPMD communication plan
# of TrainStep(gpt) proven statically on an 8-device host-platform CPU
# mesh — dp is all-reduce-only by plan, tp adds the TP all-gathers,
# train-step-int8 proves the quantized gradient sync (s8 wire dtype by
# plan, static sync bytes >= 3.5x under the f32 twin), and the
# comm-xcheck leg pins the static collective bytes to the checked-in
# runtime trace fixture within 1%. graph_lint sets the XLA device-count
# flag itself.
shard_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_SHARDLINT_TIMEOUT:-180}" \
    env JAX_PLATFORMS=cpu python tools/graph_lint.py \
    train-step-dp train-step-tp train-step-int8 comm-xcheck \
    > /tmp/_shardlint.log 2>&1
shardrc=$?
[ "$shardrc" -ne 0 ] && cat /tmp/_shardlint.log
shard_secs=$(echo "$(date +%s.%N) $shard_t0" | awk '{printf "%.2f", $1-$2}')
echo "shardlint: ${shard_secs}s (exit $shardrc)"

# sharded serving lint (ISSUE 16): the multi-chip paged engine's
# communication plan proven statically — decode/prefill/verify/COW at 4
# shards are mp-group all-reduce only (no partitioner-inserted KV
# gather), pools stay donated, the steady state never recompiles.
# graph_lint sets the XLA device-count flag itself.
sserve_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_SHARDED_SERVE_TIMEOUT:-150}" \
    env JAX_PLATFORMS=cpu python tools/graph_lint.py \
    gpt-paged-sharded > /tmp/_shardserve.log 2>&1
sservrc=$?
[ "$sservrc" -ne 0 ] && cat /tmp/_shardserve.log
sserve_secs=$(echo "$(date +%s.%N) $sserve_t0" | awk '{printf "%.2f", $1-$2}')
echo "sharded_serve_lint: ${sserve_secs}s (exit $sservrc)"

# flight-recorder smoke (ISSUE 17): toy engine + injected SLO breach ->
# exactly one trigger-pinned capture whose KernelView renders through
# /profilez byte-identical to trace_analysis, zero post-warmup jit
# misses with the recorder attached, plus the perf_diff gates (fixture
# vs itself at 0% exits 0; a planted 2x kernel slowdown is named and
# exits 1).
frec_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_FLIGHTREC_TIMEOUT:-120}" \
    env JAX_PLATFORMS=cpu python tools/flightrec_smoke.py
frecrc=$?
frec_secs=$(echo "$(date +%s.%N) $frec_t0" | awk '{printf "%.2f", $1-$2}')
echo "flightrec_smoke: ${frec_secs}s (exit $frecrc)"

# HBM-ledger smoke (ISSUE 18): toy paged engine + MemoryLedger —
# conservation vs the allocator view under churn, /memz scraped
# concurrently at zero post-warmup jit misses, an injected allocation
# failure producing a post-mortem artifact that renders through
# tools/oom_report.py, and paired mem_pressure episode rows under
# forced pool oversubscription.
memz_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_MEMZ_TIMEOUT:-120}" \
    env JAX_PLATFORMS=cpu python tools/memz_smoke.py
memzrc=$?
memz_secs=$(echo "$(date +%s.%N) $memz_t0" | awk '{printf "%.2f", $1-$2}')
echo "memz_smoke: ${memz_secs}s (exit $memzrc)"

# active-probing smoke (ISSUE 19): three toy replicas with 2 Hz
# golden-canary probers + deep invariant pollers interleaved with
# closed-loop decode — zero probe failures and zero post-warmup jit
# misses on the clean leg, probe/SLO isolation holds, one silently
# corrupted KV block is caught within one probe cycle (exactly one
# probe_fail row, pinned flight-recorder capture) and the router ejects
# the replica while the surviving fleet serves bit-identically.
probe_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_PROBE_TIMEOUT:-150}" \
    env JAX_PLATFORMS=cpu python tools/probe_smoke.py
probrc=$?
probe_secs=$(echo "$(date +%s.%N) $probe_t0" | awk '{printf "%.2f", $1-$2}')
echo "probe_smoke: ${probe_secs}s (exit $probrc)"

# comm smoke (ISSUE 20): two processes each running a 2-device CPU-mesh
# toy-GPT TrainStep(grad_comm="int8") — CommPlan compliance on the
# live executable, bit-repeatable loss across a state-restore replay
# and across processes, zero steady-state recompiles. The harness
# sets its own JAX_PLATFORMS/XLA_FLAGS per worker.
comm_t0=$(date +%s.%N)
timeout -k 10 "${TIER1_COMM_TIMEOUT:-240}" \
    python tools/comm_smoke.py
commrc=$?
comm_secs=$(echo "$(date +%s.%N) $comm_t0" | awk '{printf "%.2f", $1-$2}')
echo "comm_smoke: ${comm_secs}s (exit $commrc)"

timeout -k 10 "${TIER1_TIMEOUT:-870}" env JAX_PLATFORMS=cpu \
    PADDLE_TPU_TIER_DURATIONS="$DUR" \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
[ "$rc" -eq 0 ] && rc=$lrc
[ "$rc" -eq 0 ] && rc=$chrc
[ "$rc" -eq 0 ] && rc=$gprc
[ "$rc" -eq 0 ] && rc=$obsrc
[ "$rc" -eq 0 ] && rc=$fleetrc
[ "$rc" -eq 0 ] && rc=$fchaosrc
[ "$rc" -eq 0 ] && rc=$shardrc
[ "$rc" -eq 0 ] && rc=$sservrc
[ "$rc" -eq 0 ] && rc=$frecrc
[ "$rc" -eq 0 ] && rc=$memzrc
[ "$rc" -eq 0 ] && rc=$probrc
[ "$rc" -eq 0 ] && rc=$commrc

if [ -s "$DUR" ]; then
    python tools/check_tiers.py "$DUR" \
        --budget "${TIER1_BUDGET:-780}" \
        --slow-threshold "${TIER1_SLOW_THRESHOLD:-60}" \
        --lint-seconds "$lint_secs" \
        --lint-budget "${TIER1_LINT_BUDGET:-15}" \
        --chaos-seconds "$chaos_secs" \
        --chaos-budget "${TIER1_CHAOS_BUDGET:-120}" \
        --goodput-seconds "$goodput_secs" \
        --goodput-budget "${TIER1_GOODPUT_BUDGET:-30}" \
        --obs-seconds "$obs_secs" \
        --obs-budget "${TIER1_OBS_BUDGET:-60}" \
        --fleet-seconds "$fleet_secs" \
        --fleet-budget "${TIER1_FLEET_BUDGET:-60}" \
        --fleet-chaos-seconds "$fchaos_secs" \
        --fleet-chaos-budget "${TIER1_FLEET_CHAOS_BUDGET:-60}" \
        --shardlint-seconds "$shard_secs" \
        --shardlint-budget "${TIER1_SHARDLINT_BUDGET:-60}" \
        --sharded-serve-seconds "$sserve_secs" \
        --sharded-serve-budget "${TIER1_SHARDED_SERVE_BUDGET:-90}" \
        --flightrec-seconds "$frec_secs" \
        --flightrec-budget "${TIER1_FLIGHTREC_BUDGET:-60}" \
        --memz-seconds "$memz_secs" \
        --memz-budget "${TIER1_MEMZ_BUDGET:-60}" \
        --probe-seconds "$probe_secs" \
        --probe-budget "${TIER1_PROBE_BUDGET:-90}" \
        --comm-seconds "$comm_secs" \
        --comm-budget "${TIER1_COMM_BUDGET:-180}"
    crc=$?
    [ "$rc" -eq 0 ] && rc=$crc
else
    echo "check_tiers: no durations recorded (suite killed before any test?)"
fi
exit $rc
