"""Measured per-component step budgets via ABLATION (real step times).

The axon trace device lanes are XLA cost-model estimates (custom-calls read
0), so the only falsifiable attribution on this chip is differential: time
the full training step, then variants with one component replaced by a
stand-in, on the same protocol (fused multi-step scan, host-read fence,
best of N). The delta IS that component's wall contribution, including
whatever overlap XLA does or does not achieve.

Usage:
    python tools/step_budget.py bert   # bert-base MLM B=32 S=512
    python tools/step_budget.py gpt    # gpt3-1.3b B=3 S=2048

Variants:
  full        — the bench step
  no_ce       — LM/MLM head + CE replaced by a mean() surrogate
  no_dropout  — dropout probabilities zeroed (bert only)
  no_attn     — attention context replaced by the value projection input
                (keeps every matmul EXCEPT the S^2 attention math)
  no_ln       — LayerNorm replaced by identity (gpt only; measures the
                mean/var reductions + normalize fwd+bwd)
  sgd_opt     — optimizer swapped for bare SGD (isolates AdamW moments)
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timed(step, iters, *args):
    losses = step.run_steps(iters, *args)
    _ = float(losses.numpy()[-1])
    best = float("inf")
    for _r in range(3):
        t0 = time.perf_counter()
        losses = step.run_steps(iters, *args)
        _ = float(losses.numpy()[-1])
        best = min(best, time.perf_counter() - t0)
    return best / iters * 1e3


def bert_budget():
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import BertForMaskedLM, bert_config

    B, S, iters = 32, 512, 8
    cfg = bert_config("bert-base", max_position_embeddings=512)
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (iters, B, S)).astype("int32"))
    lbl = paddle.to_tensor(rng.randint(0, cfg.vocab_size,
                                       (iters, B, S)).astype("int64"))

    def build(loss_kind="full", drop=True):
        c = bert_config("bert-base", max_position_embeddings=512)
        if not drop:
            c.hidden_dropout = 0.0
            c.attention_dropout = 0.0
        paddle.seed(0)
        m = BertForMaskedLM(c)
        m.to(dtype="bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=m.parameters(),
                                     moment_dtype="bfloat16")
        if loss_kind == "full":
            fn = lambda a, b: m.loss(a, b, chunk_size=256)  # noqa: E731
        else:  # no_ce: encoder + mean surrogate (head+CE ablated)
            def fn(a, b):
                h = m.bert(a)
                if isinstance(h, (tuple, list)):
                    h = h[0]
                return (h.astype("float32") ** 2).mean()
        return TrainStep(m, opt, fn)

    rows = {}
    rows["full"] = timed(build(), iters, ids, lbl)
    rows["no_ce"] = timed(build("no_ce"), iters, ids, lbl)
    rows["no_dropout"] = timed(build(drop=False), iters, ids, lbl)
    print("\nbert-base MLM B=32 S=512 (ms/step):")
    for k, v in rows.items():
        print(f"  {k:12s} {v:8.2f}")
    print(f"  head+CE term      {rows['full'] - rows['no_ce']:8.2f}")
    print(f"  dropout term      {rows['full'] - rows['no_dropout']:8.2f}")


def gpt_budget():
    import paddle_tpu as paddle
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.models import GPTForCausalLM, gpt_config

    B, S, iters = 3, 2048, 8
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, 50304,
                                       (iters, B, S)).astype("int32"))

    def build(loss_kind="full", optimizer="adamw"):
        cfg = gpt_config("gpt3-1.3b", max_position_embeddings=2048)
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        m.to(dtype="bfloat16")
        if optimizer == "sgd":
            # bare SGD: p -= lr*g reads p+g, writes p — the delta vs
            # AdamW is the measured moment-state traffic + moment math
            opt = paddle.optimizer.SGD(learning_rate=1e-4,
                                       parameters=m.parameters())
        else:
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=m.parameters(),
                                         moment_dtype="bfloat16")
        if loss_kind == "full":
            fn = lambda a, b: m.loss(a, b, chunk_size=512)  # noqa: E731
        else:
            def fn(a, b):
                h = m.gpt(a)
                return (h.astype("float32") ** 2).mean()
        return TrainStep(m, opt, fn)

    def timed_no_ln():
        # LayerNorm -> identity for the WHOLE build+run: measures the
        # LN mean/var reductions + normalize fwd+bwd as a real step delta
        # (residual adds and every matmul stay)
        from paddle_tpu.nn.layers.norm import LayerNorm
        orig = LayerNorm.forward
        LayerNorm.forward = lambda self, x: x
        try:
            return timed(build(), iters, ids, ids)
        finally:
            LayerNorm.forward = orig

    rows = {}
    rows["full"] = timed(build(), iters, ids, ids)
    rows["no_ce"] = timed(build("no_ce"), iters, ids, ids)
    rows["no_ln"] = timed_no_ln()
    rows["sgd_opt"] = timed(build(optimizer="sgd"), iters, ids, ids)
    print("\ngpt3-1.3b B=3 S=2048 (ms/step):")
    for k, v in rows.items():
        print(f"  {k:12s} {v:8.2f}")
    ce = rows["full"] - rows["no_ce"]
    # FLOP floor of the three head matmuls at the step's own dense-dot
    # efficiency (~90% of 197T measured on the flagship's big dots)
    flops = 3 * 2 * B * S * 2048 * 50304
    print(f"  head+CE term      {ce:8.2f}")
    print(f"  head matmul floor {flops / 197e12 * 1e3:8.2f} (at peak), "
          f"{flops / (0.9 * 197e12) * 1e3:8.2f} (at 90%)")
    print(f"  LayerNorm term    {rows['full'] - rows['no_ln']:8.2f}")
    print(f"  AdamW-vs-SGD term {rows['full'] - rows['sgd_opt']:8.2f}")


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "bert"
    (gpt_budget if which == "gpt" else bert_budget)()
