#!/usr/bin/env python
"""Fleet chaos smoke (ISSUE 14) — the tier-1 gate for fault-tolerant
fleet serving: three in-process toy replicas behind the prefix-aware
FleetRouter, a seeded Injector killing one replica mid-traffic, and a
fault-free oracle the surviving fleet must match bitwise:

  1. the ReplicaKill fault FIRES (a green run proves recovery ran, not
     that nothing happened), the router ejects the dead replica and
     re-submits its in-flight requests elsewhere;
  2. the AutoscaleController replaces the dead replica (membership back
     at min_replicas) and later scale-down is the graceful handshake:
     begin_drain -> reroute -> remove-once-empty, never a hard kill;
  3. EVERY completed request's greedy tokens are bit-identical to the
     fault-free single-engine oracle — failover changes placement, not
     one output bit;
  4. the host-RAM spill tier cycles under the tiny prefix-cache budget:
     blocks spill, later hits REHYDRATE, and the copy count is exactly
     one host->device payload per rehydrated block;
  5. zero post-warmup jit cache misses across every replica INCLUDING
     the autoscaler's replacement (shared model = shared executables);
  6. prefix-aware routing measurably beats random routing on
     shared-prefix traffic (fleet hit-rate A/B on clean fleets).

Exit 0 = all gates hold; 1 = any violation (named on stderr).

    PYTHONPATH=. python tools/fleet_chaos_smoke.py [--requests 30] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--requests", type=int, default=30,
                    help="shared-prefix requests per leg")
    ap.add_argument("--seed", type=int, default=7,
                    help="chaos/traffic seed (the seed IS the scenario)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from paddle_tpu.inference import (AutoscaleController, FleetRouter,
                                      ReplicaRegistry, ServingConfig,
                                      ServingEngine)
    from paddle_tpu.inference.serving import shared_prefix_traffic
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.resilience import Injector, ReplicaKill

    paddle.seed(0)
    gcfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                     num_heads=2, max_position_embeddings=64,
                     intermediate_size=64)
    # one toy model, every replica (and the oracle, and the autoscaler's
    # replacement) shares its executables — warmup once covers the fleet
    model = GPTForCausalLM(gcfg)
    model.eval()
    KB = 4
    from paddle_tpu.inference import BlockPool
    BPB = BlockPool.for_model(model, num_blocks=2,
                              block_size=KB).bytes_per_block

    def mk(spill: bool = True) -> ServingEngine:
        # a 3-block device budget under 3 prefixes x 2 blocks forces
        # constant LRU eviction -> the spill tier cycles for real
        return ServingEngine(model, ServingConfig(
            max_batch=2, prompt_cap=16, max_new_tokens=6, decode_chunk=3,
            paged=True, prefix_cache=True, kv_block=KB, kv_blocks=48,
            prefix_cache_bytes=3 * BPB if spill else None,
            spill_host_bytes=1 << 22 if spill else None))

    traffic = shared_prefix_traffic(
        args.requests, n_prefixes=3, prefix_len=2 * KB, prompt_cap=16,
        vocab_size=gcfg.vocab_size, rate=1e9, seed=args.seed)
    prompts = [t["prompt"] for t in traffic]

    failures = []

    # ---------------------------------------------- fault-free oracle
    oracle_eng = mk(spill=False)
    oracle = {}
    for p in prompts:
        r = oracle_eng.submit(p)
        oracle_eng.drain()
        if r.status != "done":
            failures.append(f"oracle refused a prompt: {r.reason}")
        oracle[p.tobytes()] = r.tokens

    # ------------------------------------------------------ chaos leg
    chaos = Injector(args.seed, faults=[ReplicaKill("r1", step=2)])
    reg = ReplicaRegistry({f"r{i}": mk() for i in range(3)}, chaos=chaos)
    # warm every executable (prefill/suffix/COW/decode + the spill d2h
    # gather and rehydrate h2d scatter) BEFORE the miss snapshot
    for h in reg.handles():
        h.engine.warmup_prefix_cache(gcfg.vocab_size)
    miss0 = compile_cache_misses()

    router = FleetRouter(reg, policy="prefix", chaos=chaos,
                         retry_budget_s=5.0, seed=args.seed)
    # queue-depth/goodput triggers disabled: the ONLY spawn signal left
    # is membership-below-min, so the replacement decision is
    # deterministically a "replace" (the burst backlog would otherwise
    # legitimately scale_up first and mask it)
    auto = AutoscaleController(reg, lambda name: mk(),
                               min_replicas=3, max_replicas=4,
                               scale_up_queue_depth=1e9,
                               goodput_floor=0.0)
    freqs = [router.submit(p) for p in prompts]
    router.drain(tick=auto.tick)

    if chaos.fired("replica_kill") != 1:
        failures.append("ReplicaKill never fired — the scenario tested "
                        "nothing")
    if "r1" not in reg.ejected:
        failures.append("dead replica r1 was not ejected")
    if router.counters["redispatched"] < 1:
        failures.append("no in-flight request was redispatched off the "
                        "dead replica")
    if not any(d["action"] == "replace" for d in auto.decisions):
        failures.append("autoscaler never replaced the dead replica")
    if len(reg.names(("serving",))) != 3:
        failures.append(f"fleet did not recover to min_replicas=3 "
                        f"(serving={reg.names(('serving',))})")
    bad = [f for f in freqs if f.status != "done"]
    if bad:
        failures.append(f"{len(bad)} requests did not complete: "
                        f"{[(f.status, f.reason) for f in bad[:3]]}")
    mismatch = sum(1 for f in freqs if f.status == "done" and
                   not np.array_equal(f.tokens, oracle[f.prompt.tobytes()]))
    if mismatch:
        failures.append(f"{mismatch} completed requests differ from the "
                        f"fault-free oracle (must be bit-identical)")

    spilled = rehydrated = h2d = 0
    for h in list(reg.handles(("serving", "draining"))) + \
            list(reg.ejected.values()):
        t = h.engine._spill
        if t is not None:
            spilled += t.spilled_total
            rehydrated += t.rehydrated_total
            h2d += t.h2d_copies
    if spilled < 1 or rehydrated < 1:
        failures.append(f"spill tier never cycled (spilled={spilled}, "
                        f"rehydrated={rehydrated}) — shrink the budget")
    if h2d != rehydrated:
        failures.append(f"rehydrate copy count {h2d} != rehydrated "
                        f"blocks {rehydrated} (must be ONE host->device "
                        f"copy per block)")

    dm = compile_cache_misses() - miss0
    if dm:
        failures.append(f"{dm} post-warmup jit cache misses across the "
                        f"fleet incl. the replacement replica (must be 0)")

    # graceful scale-down: with the floor lowered, idle ticks drain the
    # least-loaded member and remove it only once empty
    down = AutoscaleController(reg, lambda name: mk(), min_replicas=2,
                               max_replicas=4,
                               idle_ticks_before_scale_down=2)
    victim = None
    for _ in range(8):
        rec = down.tick()
        if rec["action"] == "scale_down_begin":
            victim = reg.handle(rec["replica"])
        router.step()
    acts = [d["action"] for d in down.decisions]
    if "scale_down_begin" not in acts or "scale_down_done" not in acts:
        failures.append(f"graceful scale-down did not complete: {acts}")
    elif victim is not None and (victim.engine.busy
                                 or victim.engine.queue_depth):
        failures.append("scale-down removed a replica that still had "
                        "work (hard kill!)")
    if len(reg.names(("serving",))) != 2:
        failures.append(f"scale-down did not land at min_replicas=2 "
                        f"(serving={reg.names(('serving',))})")

    # ------------------------------------------------ routing A/B leg
    def hit_rate(policy: str) -> float:
        r = ReplicaRegistry({f"ab{i}": mk(spill=False)
                             for i in range(3)})
        rt = FleetRouter(r, policy=policy, retry_budget_s=5.0,
                         seed=args.seed)
        for p in prompts:
            rt.submit(p)
        rt.drain()
        return rt.fleet_prefix_stats()["hit_rate"] or 0.0

    prefix_rate = hit_rate("prefix")
    random_rate = hit_rate("random")
    if not prefix_rate > random_rate:
        failures.append(f"prefix routing ({prefix_rate:.3f}) does not "
                        f"beat random routing ({random_rate:.3f}) on "
                        f"shared-prefix traffic")

    out = {"requests": len(freqs),
           "completed": sum(1 for f in freqs if f.status == "done"),
           "redispatched": router.counters["redispatched"],
           "replicas_lost": router.counters["replicas_lost"],
           "spilled_blocks": spilled, "rehydrated_blocks": rehydrated,
           "rehydrate_h2d_copies": h2d,
           "post_warmup_jit_misses": dm,
           "prefix_hit_rate": round(prefix_rate, 4),
           "random_hit_rate": round(random_rate, 4),
           "ok": not failures, "failures": failures}
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        print(f"fleet_chaos_smoke: {out['completed']}/{out['requests']} "
              f"requests bit-identical to oracle through a replica kill "
              f"({out['redispatched']} redispatched); spill "
              f"{spilled}->rehydrate {rehydrated} ({h2d} h2d copies); "
              f"post-warmup jit misses {dm}; hit rate prefix "
              f"{prefix_rate:.3f} vs random {random_rate:.3f}")
    for f in failures:
        print(f"fleet_chaos_smoke: VIOLATION: {f}", file=sys.stderr)
    if not failures:
        print("fleet_chaos_smoke: OK")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
