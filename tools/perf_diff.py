#!/usr/bin/env python
"""perf_diff — kernel-level regression attribution between two captures.

The flight recorder (paddle_tpu.obs.flightrec) answers "what did the
anomaly's steps look like"; this CLI answers the follow-up the vision
hot-path and comm-overlap roadmap items are blocked on: WHICH kernels
got slower between two captures. Inputs are trace files, directories of
captures (newest trace wins — a flight-recorder dir or a BENCH
revision's profile dir work as-is), and the output is a per-op table:

  - per-op Δ device time (per step when --steps-* is given, so captures
    of different lengths compare)
  - Δ occupancy of the step (the op's share of total device time)
  - new / vanished kernels (a fusion that split is a new+vanished pair)
  - per-collective EXPOSED-time deltas (the wall the step pays)

`--regress-pct P` turns the report into a gate: exit 1 naming every
common kernel whose per-step time grew more than P percent (and every
new kernel) above the `--min-us` noise floor. A capture diffed against
itself reports 0% everywhere and exits 0 at any threshold.

    python tools/perf_diff.py BASELINE CANDIDATE [--steps N]
        [--steps-a N] [--steps-b N] [--regress-pct 5] [--min-us 50]
        [--top 30] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("baseline", help="trace file or directory of "
                    "captures (newest *.trace.json[.gz] wins)")
    ap.add_argument("candidate", help="trace file or directory")
    ap.add_argument("--steps", type=int, default=None,
                    help="steps in BOTH captures (normalizes totals to "
                         "per-step figures)")
    ap.add_argument("--steps-a", type=int, default=None,
                    help="steps in the baseline capture")
    ap.add_argument("--steps-b", type=int, default=None,
                    help="steps in the candidate capture")
    ap.add_argument("--regress-pct", type=float, default=None,
                    help="gate: exit 1 when any common kernel's "
                         "per-step time grew MORE than this percent "
                         "(or a new kernel appeared) above --min-us")
    ap.add_argument("--min-us", type=float, default=50.0,
                    help="noise floor for the gate: per-step device "
                         "microseconds below which deltas/new kernels "
                         "are ignored (default 50)")
    ap.add_argument("--top", type=int, default=30,
                    help="kernel rows to print (default 30)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as JSON instead of tables")
    args = ap.parse_args(argv)

    from paddle_tpu.profiler.trace_analysis import (analyze,
                                                    diff_regressions,
                                                    format_kernel_diff,
                                                    kernel_diff)
    an_a = analyze(args.baseline,
                   steps=args.steps_a if args.steps_a is not None
                   else args.steps)
    an_b = analyze(args.candidate,
                   steps=args.steps_b if args.steps_b is not None
                   else args.steps)
    if not an_a.device_events or not an_b.device_events:
        print("perf_diff: a capture has no device-lane events "
              f"(baseline {len(an_a.device_events)}, candidate "
              f"{len(an_b.device_events)}) — nothing to attribute",
              file=sys.stderr)
        return 2
    diff = kernel_diff(an_a, an_b)
    if args.json:
        print(json.dumps(diff, indent=2))
    else:
        print(format_kernel_diff(diff, top=args.top))
    if args.regress_pct is None:
        return 0
    regs = diff_regressions(diff, regress_pct=args.regress_pct,
                            min_us=args.min_us)
    for r in regs:
        print(f"perf_diff: REGRESSION: {r['name']} "
              f"[{r['category']}] {r['reason']} "
              f"({r['a_us'] / 1e3:.3f} -> {r['b_us'] / 1e3:.3f} "
              f"ms/step)", file=sys.stderr)
    if regs:
        print(f"perf_diff: {len(regs)} kernel(s) over the "
              f"{args.regress_pct:g}% gate", file=sys.stderr)
        return 1
    print(f"perf_diff: OK — no kernel over the {args.regress_pct:g}% "
          f"gate (floor {args.min_us:g}us/step)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
