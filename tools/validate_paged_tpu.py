"""Hardware validation for the paged attention kernels (run on TPU).

CPU CI exercises the Pallas kernels in interpret mode only (tests/
test_paged_kv.py, tests/test_spec_decode.py); Mosaic compilation and the
scalar-prefetched block-table fetch path are checked here on the chip:
  1. compiled kernel parity vs `paged_attention_reference` across ragged
     lengths (incl. a row at an exact block boundary and a dummy row)
  2. MULTI-TOKEN kernel parity (ISSUE 11) vs the gather reference across
     (k, block, start) shapes — k=1 degenerate, windows starting at and
     crossing block boundaries, serving-scale geometry
  3. serving-shape sweep (gpt3-1.3b geometry: nh=16 hd=128, bf16 pool)
  4. end-to-end: paged engine greedy == generate_static_ragged per row
     (plain AND speculative), zero steady jit cache misses
  5. ``--shards N`` (ISSUE 16): sharded-parity mode — the SAME traffic
     through the head-sharded tensor-parallel engine on an N-chip mp
     mesh and the 1-chip engine; greedy output must be bit-identical,
     pools must carry the head sharding, steady state must not recompile

Usage: python tools/validate_paged_tpu.py [--shards N]
"""
import argparse
import sys

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


def check(name, ok, detail=""):
    print(f"[{'PASS' if ok else 'FAIL'}] {name} {detail}")
    if not ok:
        sys.exit(1)


def kernel_parity(dtype, nh, hd, bs, tol):
    from paddle_tpu.ops.attention import paged_attention_reference
    from paddle_tpu.ops.pallas.paged_attention import paged_attention_kernel
    rng = np.random.RandomState(0)
    B, NB, MB = 4, 32, 6
    kp = jnp.asarray(rng.randn(NB, bs, nh, hd).astype(np.float32) * 0.3,
                     dtype)
    vp = jnp.asarray(rng.randn(NB, bs, nh, hd).astype(np.float32) * 0.3,
                     dtype)
    lens = jnp.asarray([1, bs, 2 * bs + 3, 0], jnp.int32)  # boundary + dummy
    tables = np.zeros((B, MB), np.int32)
    tables[0, :1] = [1]
    tables[1, :1] = [2]
    tables[2, :3] = [3, 4, 5]
    tables = jnp.asarray(tables)
    q = jnp.asarray(rng.randn(B, 1, nh, hd).astype(np.float32) * 0.3, dtype)
    got = np.asarray(paged_attention_kernel(q, kp, vp, tables, lens),
                     np.float32)
    want = np.asarray(paged_attention_reference(q, kp, vp, tables, lens),
                      np.float32)
    live = slice(0, 3)        # dummy row: kernel zeros vs reference garbage
    err = np.abs(got[live] - want[live]).max()
    check(f"kernel parity {dtype} nh={nh} hd={hd} bs={bs}", err < tol,
          f"max err {err:.2e}")


def kernel_prefix_parity(dtype, nh, hd, bs, s, starts, tol):
    """Multi-token [B, k] kernel vs the gather reference (ISSUE 11):
    per-row start offsets as data, causal-within-window masking."""
    from paddle_tpu.ops.attention import (paged_prefill_write,
                                          paged_prefix_attention_reference)
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_prefix_attention_kernel)
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    B, MB = len(starts), 6
    nb = 1 + B * MB
    kp = jnp.zeros((nb, bs, nh, hd), dtype)
    vp = jnp.zeros_like(kp)
    tables = jnp.asarray(
        np.arange(1, nb, dtype=np.int32).reshape(B, MB))
    K = rng.randn(B, MB * bs, nh, hd).astype(np.float32) * 0.3
    V = rng.randn(B, MB * bs, nh, hd).astype(np.float32) * 0.3
    for b in range(B):
        kp = paged_prefill_write(kp, jnp.asarray(K[b:b + 1], dtype),
                                 tables[b:b + 1])
        vp = paged_prefill_write(vp, jnp.asarray(V[b:b + 1], dtype),
                                 tables[b:b + 1])
    q = jnp.asarray(rng.randn(B, s, nh, hd).astype(np.float32) * 0.3,
                    dtype)
    st = jnp.asarray(starts, jnp.int32)
    got = np.asarray(paged_prefix_attention_kernel(q, kp, vp, tables, st),
                     np.float32)
    want = np.asarray(
        paged_prefix_attention_reference(q, kp, vp, tables, st),
        np.float32)
    err = np.abs(got - want).max()
    check(f"multi-token kernel parity {dtype} nh={nh} hd={hd} bs={bs} "
          f"k={s} starts={list(starts)}", err < tol, f"max err {err:.2e}")


def spec_engine_parity():
    """Speculative engine greedy == generate_static_ragged on repeated
    traffic, full trie acceptance, zero steady jit cache misses."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import (ServingConfig, ServingEngine,
                                      repeated_traffic)
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=256, num_layers=2,
                    num_heads=2, max_position_embeddings=512,
                    intermediate_size=512)
    m = GPTForCausalLM(cfg)
    m.eval()                     # f32: same numerics-class note as above
    CAP, NEW = 64, 16
    # kv_block=8 < NEW: trie drafts are block-granular, so a finished
    # chain only contributes drafts once its generated tokens fill at
    # least one pool block past the prompt
    eng = ServingEngine(m, ServingConfig(
        max_batch=2, prompt_cap=CAP, max_new_tokens=NEW, decode_chunk=4,
        paged=True, kv_block=8, kv_blocks=256, prefix_cache=True,
        spec_decode=True, spec_k=4))
    eng.warmup_prefix_cache(cfg.vocab_size, clear=False)
    traffic = repeated_traffic(8, n_prompts=2, prompt_len=CAP,
                               vocab_size=cfg.vocab_size, rate=1e9,
                               seed=5)
    prompts = {t["prompt_id"]: t["prompt"] for t in traffic}
    ids = np.stack([prompts[i] for i in sorted(prompts)])
    ref = m.generate_static_ragged(paddle.to_tensor(ids),
                                   [CAP] * len(ids),
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    miss0 = compile_cache_misses()
    for t in traffic:
        eng.submit(t["prompt"])
    done = eng.drain()
    ok = all(r.status == "done" for r in done)
    for r in done:
        row = next(i for i in sorted(prompts)
                   if np.array_equal(prompts[i], r.prompt))
        ok = ok and np.array_equal(r.tokens, ref[row])
    check("spec engine greedy == generate_static_ragged", ok)
    s = eng.metrics.counters
    check("spec windows drafted from the trie",
          s["spec_windows"] > 0 and s["spec_drafts_trie"] > 0,
          f"windows={s['spec_windows']} accepted={s['spec_accepted']}/"
          f"{s['spec_proposed']}")
    check("steady speculative loop: zero jit cache misses",
          compile_cache_misses() - miss0 == 0,
          f"recompiles={eng.monitor.recompiles}")


def engine_parity():
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=256, num_layers=2,
                    num_heads=2, max_position_embeddings=512,
                    intermediate_size=512)
    m = GPTForCausalLM(cfg)
    # f32 deliberately: the static reference stores scores in the MODEL
    # dtype (bf16 under .to("bfloat16")) while the paged kernel always
    # keeps f32 scores — bit-exact greedy comparison needs both sides in
    # the same numerics class. bf16 KERNEL numerics are covered by the
    # kernel_parity sweeps above.
    m.eval()
    CAP, NEW = 64, 16
    lens = [64, 17, 3, 40, 1, 33]
    rng = np.random.RandomState(1)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    eng = ServingEngine(m, ServingConfig(max_batch=2, prompt_cap=CAP,
                                         max_new_tokens=NEW,
                                         decode_chunk=4, paged=True,
                                         kv_block=16))
    eng.submit(ids[0, :lens[0]])
    eng.drain()
    miss0 = compile_cache_misses()
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    ok = all(r.status == "done" for r in done)
    for r in done:
        row = next(i for i in range(len(lens))
                   if np.array_equal(ids[i, :lens[i]], r.prompt))
        ok = ok and np.array_equal(r.tokens, ref[row])
    check("paged engine greedy == generate_static_ragged", ok)
    check("steady mixed-length loop: zero jit cache misses",
          compile_cache_misses() - miss0 == 0,
          f"recompiles={eng.monitor.recompiles}")


def sharded_engine_parity(shards):
    """Sharded-parity mode (ISSUE 16): greedy output bit-identical at
    shards=1 vs shards=N on the chip mesh, head-sharded pools, zero
    steady jit cache misses on the sharded engine. The collective
    inventory itself is proven statically by
    `tools/graph_lint.py gpt-paged-sharded`; this checks the numerics
    on real chips."""
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    ndev = len(jax.devices())
    check(f"--shards {shards}: enough local devices", shards <= ndev,
          f"({ndev} available)")
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=256, num_layers=2,
                    num_heads=max(4, shards),  # divisible head count
                    max_position_embeddings=512,
                    intermediate_size=512)
    m = GPTForCausalLM(cfg)
    m.eval()                     # f32: same numerics-class note as above
    CAP, NEW = 64, 16
    lens = [64, 17, 3, 40]
    rng = np.random.RandomState(1)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)

    def serve(s):
        eng = ServingEngine(m, ServingConfig(
            max_batch=2, prompt_cap=CAP, max_new_tokens=NEW,
            decode_chunk=4, paged=True, kv_block=16, shards=s))
        for i, ln in enumerate(lens):
            eng.submit(ids[i, :ln])
        eng.drain()
        miss0 = compile_cache_misses()
        for i, ln in enumerate(lens):
            eng.submit(ids[i, :ln])
        toks = {tuple(r.prompt.tolist()): list(r.tokens)
                for r in eng.drain()}
        return eng, toks, compile_cache_misses() - miss0

    _, one, _ = serve(1)
    eng, got, miss = serve(shards)
    check(f"sharded (mp={shards}) greedy == single-chip greedy",
          one == got)
    specs = {str(getattr(p.sharding, "spec", None))
             for layer in eng._pools for p in layer}
    check("pools carry the mp head sharding",
          all("'mp'" in s for s in specs), f"specs={sorted(specs)}")
    check("steady sharded loop: zero jit cache misses", miss == 0,
          f"recompiles={eng.monitor.recompiles}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, default=None, metavar="N",
                    help="also run the sharded-parity suite on an "
                         "N-chip mp mesh (ISSUE 16)")
    args = ap.parse_args()
    dev = jax.devices()[0]
    print("device:", dev)
    if dev.platform not in ("tpu", "axon"):
        print("no TPU — run this on the chip (CPU CI covers interpret "
              "mode)")
        sys.exit(2)
    kernel_parity(jnp.float32, nh=4, hd=64, bs=16, tol=2e-5)
    kernel_parity(jnp.bfloat16, nh=16, hd=128, bs=16, tol=2e-2)
    kernel_parity(jnp.bfloat16, nh=12, hd=64, bs=32, tol=2e-2)
    # multi-token (ISSUE 11): k=1 degenerate, boundary-start, boundary-
    # crossing windows, serving-scale geometry + a wide prefill window
    kernel_prefix_parity(jnp.float32, nh=4, hd=64, bs=16, s=1,
                         starts=(40, 16, 0), tol=2e-5)
    kernel_prefix_parity(jnp.float32, nh=4, hd=64, bs=16, s=8,
                         starts=(16, 13, 0), tol=2e-5)
    kernel_prefix_parity(jnp.bfloat16, nh=16, hd=128, bs=16, s=8,
                         starts=(32, 5, 0), tol=2e-2)
    kernel_prefix_parity(jnp.bfloat16, nh=16, hd=128, bs=16, s=64,
                         starts=(16, 0, 7), tol=2e-2)
    engine_parity()
    spec_engine_parity()
    if args.shards:
        sharded_engine_parity(args.shards)
    print("all paged serving validations passed")


if __name__ == "__main__":
    main()
