"""Hardware validation for the paged decode attention kernel (run on TPU).

CPU CI exercises the Pallas kernel in interpret mode only (tests/
test_paged_kv.py); Mosaic compilation and the scalar-prefetched
block-table fetch path are checked here on the real chip:
  1. compiled kernel parity vs `paged_attention_reference` across ragged
     lengths (incl. a row at an exact block boundary and a dummy row)
  2. serving-shape sweep (gpt3-1.3b geometry: nh=16 hd=128, bf16 pool)
  3. end-to-end: paged engine greedy == generate_static_ragged per row
  4. a steady mixed-length engine loop adds zero jit cache misses

Usage: python tools/validate_paged_tpu.py
"""
import sys

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


def check(name, ok, detail=""):
    print(f"[{'PASS' if ok else 'FAIL'}] {name} {detail}")
    if not ok:
        sys.exit(1)


def kernel_parity(dtype, nh, hd, bs, tol):
    from paddle_tpu.ops.attention import paged_attention_reference
    from paddle_tpu.ops.pallas.paged_attention import paged_attention_kernel
    rng = np.random.RandomState(0)
    B, NB, MB = 4, 32, 6
    kp = jnp.asarray(rng.randn(NB, bs, nh, hd).astype(np.float32) * 0.3,
                     dtype)
    vp = jnp.asarray(rng.randn(NB, bs, nh, hd).astype(np.float32) * 0.3,
                     dtype)
    lens = jnp.asarray([1, bs, 2 * bs + 3, 0], jnp.int32)  # boundary + dummy
    tables = np.zeros((B, MB), np.int32)
    tables[0, :1] = [1]
    tables[1, :1] = [2]
    tables[2, :3] = [3, 4, 5]
    tables = jnp.asarray(tables)
    q = jnp.asarray(rng.randn(B, 1, nh, hd).astype(np.float32) * 0.3, dtype)
    got = np.asarray(paged_attention_kernel(q, kp, vp, tables, lens),
                     np.float32)
    want = np.asarray(paged_attention_reference(q, kp, vp, tables, lens),
                      np.float32)
    live = slice(0, 3)        # dummy row: kernel zeros vs reference garbage
    err = np.abs(got[live] - want[live]).max()
    check(f"kernel parity {dtype} nh={nh} hd={hd} bs={bs}", err < tol,
          f"max err {err:.2e}")


def engine_parity():
    import paddle_tpu as paddle
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=256, num_layers=2,
                    num_heads=2, max_position_embeddings=512,
                    intermediate_size=512)
    m = GPTForCausalLM(cfg)
    # f32 deliberately: the static reference stores scores in the MODEL
    # dtype (bf16 under .to("bfloat16")) while the paged kernel always
    # keeps f32 scores — bit-exact greedy comparison needs both sides in
    # the same numerics class. bf16 KERNEL numerics are covered by the
    # kernel_parity sweeps above.
    m.eval()
    CAP, NEW = 64, 16
    lens = [64, 17, 3, 40, 1, 33]
    rng = np.random.RandomState(1)
    ids = rng.randint(1, cfg.vocab_size, (len(lens), CAP)).astype(np.int64)
    for r, ln in enumerate(lens):
        ids[r, ln:] = 0
    ref = m.generate_static_ragged(paddle.to_tensor(ids), lens,
                                   max_new_tokens=NEW).numpy()[:, CAP:]
    eng = ServingEngine(m, ServingConfig(max_batch=2, prompt_cap=CAP,
                                         max_new_tokens=NEW,
                                         decode_chunk=4, paged=True,
                                         kv_block=16))
    eng.submit(ids[0, :lens[0]])
    eng.drain()
    miss0 = compile_cache_misses()
    for i in range(len(lens)):
        eng.submit(ids[i, :lens[i]])
    done = eng.drain()
    ok = all(r.status == "done" for r in done)
    for r in done:
        row = next(i for i in range(len(lens))
                   if np.array_equal(ids[i, :lens[i]], r.prompt))
        ok = ok and np.array_equal(r.tokens, ref[row])
    check("paged engine greedy == generate_static_ragged", ok)
    check("steady mixed-length loop: zero jit cache misses",
          compile_cache_misses() - miss0 == 0,
          f"recompiles={eng.monitor.recompiles}")


def main():
    dev = jax.devices()[0]
    print("device:", dev)
    if dev.platform not in ("tpu", "axon"):
        print("no TPU — run this on the chip (CPU CI covers interpret "
              "mode)")
        sys.exit(2)
    kernel_parity(jnp.float32, nh=4, hd=64, bs=16, tol=2e-5)
    kernel_parity(jnp.bfloat16, nh=16, hd=128, bs=16, tol=2e-2)
    kernel_parity(jnp.bfloat16, nh=12, hd=64, bs=32, tol=2e-2)
    engine_parity()
    print("all paged serving validations passed")


if __name__ == "__main__":
    main()
