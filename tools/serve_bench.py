#!/usr/bin/env python
"""Serving benchmark — replay open-loop traffic through the ServingEngine
and report latency percentiles + SLO attainment.

The serving analog of bench.py: where bench rows measure training
step-time/MFU, this measures the signals a serving deployment is judged by
(PAPERS.md serving studies): TTFT / per-output-token / end-to-end latency
distributions under load, queue wait, batch fill, KV occupancy, and the
fraction of requests meeting their SLOs. Traffic is OPEN-LOOP (Poisson
arrivals at --rate req/s, scheduled independently of service speed) so
queueing shows up honestly: a single-threaded replayer submits each
request with its SCHEDULED arrival timestamp (`enqueue_at`), then serves
whatever is queued — exactly the accounting a load balancer would see.

    PYTHONPATH=. python tools/serve_bench.py \
        [--preset gpt3-125m] --requests 64 --rate 100 \
        --batch 4 --prompt-cap 16 --new 8 \
        --slo-ttft-ms 500 --slo-e2e-ms 2000 [--json] [--metrics]

Paged serving (ISSUE 5): ``--paged`` runs the block-pool engine
(slot-level continuous batching, mid-flight admission); ``--compare``
replays the SAME traffic through both engines and prints the
padded-vs-paged table (tok/s, p99 TTFT, true KV occupancy) — int8 KV
(``--int8-cache``) now runs on BOTH legs (the paged int8 pool landed in
ISSUE 10; only non-int8 narrow dtypes still refuse with a structured
finding). ``--length-dist longtail`` draws Pareto-shaped prompt lengths
— the mostly-short-with-heavy-tail mix where right-padding wastes the
most HBM and paging shows its gap.

Prefix cache (ISSUE 10): ``--shared-prefix N`` switches the workload to
N fixed system prompts (``--prefix-len`` tokens each) x Poisson-arriving
random suffixes, and replays it through the paged engine with the prefix
cache OFF and ON — printing hit rate, prefill-tokens-saved and the
TTFT-with/without-cache table. ``--prefix-cache`` alone enables the
cache on a plain ``--paged`` run.

Speculative decoding (ISSUE 11): ``--spec`` replays the workload through
the paged+prefix engine with speculative decode OFF and ON (``--spec-k``
drafts per verify window, prompt-lookup drafting from the trie) and
prints the acceptance table. ``--repeat N`` switches the workload to N
fixed prompts repeated verbatim — the agentic/retry shape where trie
drafting accepts end-to-end.

SLO gate (ISSUE 12): ``--slo "ttft_p99=500ms,e2e_p99=2s,goodput=0.95"``
evaluates the declarative targets as whole-run burn rates over the
replayed traffic's log-bucket histograms (obs.slo), prints the burn-rate
table, and exits NONZERO on any breach — the same exit-code convention
as the steady-state-recompile gate, so BENCH rows carry SLO attainment.
Under an A/B mode the gate judges the LAST leg (the feature-on engine).

Without --preset a 2-layer toy GPT runs on CPU (CI-sized); with a preset
set PADDLE_TPU_EXAMPLE_TPU=1 to run real-chip sizes.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(preset):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        intermediate_size=128)
    model = GPTForCausalLM(cfg)
    if os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        model.to(dtype="bfloat16")
    model.eval()
    return model, cfg


def _serving_config(args, paged, prefix_cache=False, spec=False):
    from paddle_tpu.inference import ServingConfig
    # int8 KV runs on BOTH --compare legs now (the paged int8 pool landed
    # in ISSUE 10); a cache dtype the paged engine still cannot serve gets
    # the structured config-validation finding explaining why
    return ServingConfig(max_batch=args.batch, prompt_cap=args.prompt_cap,
                         max_new_tokens=args.new,
                         decode_chunk=args.decode_chunk,
                         queue_capacity=args.queue_capacity,
                         eos_token_id=args.eos,
                         weight_dtype="int8" if args.int8_weights else None,
                         cache_dtype="int8" if args.int8_cache else None,
                         paged=paged, kv_block=args.kv_block,
                         kv_blocks=args.kv_blocks,
                         prefix_cache=prefix_cache,
                         prefix_cache_bytes=args.prefix_cache_bytes,
                         spec_decode=spec, spec_k=args.spec_k,
                         # paged-only knobs: --compare's padded leg must
                         # not trip the config validation on them
                         prefill_chunk=args.prefill_chunk if paged
                         else None,
                         shards=args.shards if paged else None)


def _make_traffic(args, cfg, *, n, rate, seed):
    from paddle_tpu.inference import (repeated_traffic,
                                      shared_prefix_traffic,
                                      synthetic_traffic)
    if args.repeat:
        return repeated_traffic(n, n_prompts=args.repeat,
                                prompt_len=args.prompt_cap,
                                vocab_size=cfg.vocab_size, rate=rate,
                                seed=seed)
    if args.shared_prefix:
        return shared_prefix_traffic(
            n, n_prefixes=args.shared_prefix, prefix_len=args.prefix_len,
            prompt_cap=args.prompt_cap, vocab_size=cfg.vocab_size,
            rate=rate, seed=seed)
    return synthetic_traffic(n, prompt_cap=args.prompt_cap,
                             vocab_size=cfg.vocab_size, rate=rate,
                             seed=seed, length_dist=args.length_dist)


def run_engine(model, cfg, args, *, paged, prefix_cache=False,
               spec=False):
    """Replay the workload through one engine; returns (report, engine)."""
    from paddle_tpu.inference import ServingEngine
    engine = ServingEngine(model,
                           _serving_config(args, paged, prefix_cache,
                                           spec))

    # warmup batch: compiles the (prefill + chunk) executables once, so the
    # measured replay is the steady state a long-lived server sits in.
    # With the prefix cache the warmup must also touch the suffix-prefill
    # and COW executables — engine.warmup_prefix_cache runs the whole
    # choreography and drops its cached prefixes so the replay starts cold.
    warm = _make_traffic(args, cfg, n=max(args.batch, 2), rate=1e9, seed=1)
    for item in warm:
        engine.submit(item["prompt"])
    engine.drain()
    if prefix_cache:
        engine.warmup_prefix_cache(cfg.vocab_size)
    engine.metrics = type(engine.metrics)()     # fresh aggregates

    traffic = _make_traffic(args, cfg, n=args.requests, rate=args.rate,
                            seed=args.seed)
    t0 = engine.clock()
    finished = []
    peak_kv = 0.0

    def _track():
        nonlocal peak_kv
        kv = engine.metrics.gauges.get("kv_occupancy")
        if kv is not None:
            peak_kv = max(peak_kv, kv)

    for item in traffic:
        due = t0 + item["at"]
        wait = due - engine.clock()
        if wait > 0:                   # open loop: arrivals keep schedule
            time.sleep(wait)
        # when serving fell BEHIND the schedule, enqueue_at backdates the
        # queue-wait span to the scheduled arrival — the load-balancer view
        engine.submit(item["prompt"], enqueue_at=due)
        while engine.queue_depth >= args.batch:
            finished.extend(engine.step())
            _track()
    while engine.busy:
        finished.extend(engine.step())
        _track()
    wall = engine.clock() - t0

    done = [r for r in finished if r.status == "done"]
    # timed-out traffic counts as an SLO MISS, not a dropped sample —
    # excluding it would report 100% attainment exactly under overload
    n_expired = sum(1 for r in finished if r.status == "timeout")
    ttfts = [r.trace.ttft_s for r in done if r.trace.ttft_s is not None]
    e2es = [r.trace.e2e_s for r in done if r.trace.e2e_s is not None]

    def attainment(vals, limit_ms):
        denom = len(vals) + n_expired
        if not denom:
            return None
        return sum(1 for t in vals if t * 1e3 <= limit_ms) / denom

    slo = {
        "ttft_ms": args.slo_ttft_ms,
        "e2e_ms": args.slo_e2e_ms,
        "expired": n_expired,
        "ttft_attainment": attainment(ttfts, args.slo_ttft_ms),
        "e2e_attainment": attainment(e2es, args.slo_e2e_ms),
    }
    s = engine.summary()
    mode = "paged" if paged else "padded"
    if prefix_cache:
        mode += "+prefix"
    if spec:
        mode += "+spec"
    if paged and args.shards and args.shards > 1:
        mode += f"+mp{args.shards}"
    out = {"mode": mode,
           "preset": args.preset or "toy", "requests": args.requests,
           "rate_req_s": args.rate, "length_dist": args.length_dist,
           "wall_s": round(wall, 3),
           "completed": len(done),
           "throughput_tok_s": round(s["tokens_out_total"] / wall, 1)
           if wall > 0 else None,
           "kv_occupancy_peak": round(peak_kv, 4),
           "slo": slo, "serving": s}
    if paged and args.shared_prefix:
        hits, misses = s["prefix_hit_total"], s["prefix_miss_total"]
        out["prefix"] = {
            "enabled": prefix_cache,
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 4),
            "prefill_tokens_saved": s["prefill_tokens_saved_total"],
        }
    if spec:
        prop = s["spec_proposed_total"]
        out["spec"] = {
            "windows": s["spec_windows_total"],
            "proposed": prop, "accepted": s["spec_accepted_total"],
            "accept_rate": round(s["spec_accepted_total"] / prop, 4)
            if prop else None,
            "drafts_trie": s["spec_drafts_trie_total"],
            "drafts_model": s["spec_drafts_model_total"],
            "accept_len": s.get("spec_accept_len"),
        }
    # the recompiles counter is a pure churn signal: refused requests log
    # their shape delta without feeding it (record_compile count=False)
    out["steady_recompiles"] = engine.monitor.recompiles
    return out, engine


def run_bench(args):
    """Returns ([report, ...], engine_of_last_run) — one report per engine
    mode (two under --compare / --shared-prefix)."""
    model, cfg = build_model(args.preset)
    if args.spec:
        # the speculative A/B (ISSUE 11): same traffic, paged+prefix
        # engine, spec decode off then on
        modes = [(True, True, False), (True, True, True)]
    elif args.shared_prefix:
        # the prefix-cache A/B: same system-prompt traffic, paged engine,
        # cache off then on
        modes = [(True, False, False), (True, True, False)]
    elif args.compare:
        modes = [(False, False, False), (True, args.prefix_cache, False)]
    else:
        modes = [(args.paged, args.prefix_cache, False)]
    reports = []
    engine = None
    for paged, prefix, spec in modes:
        rep, engine = run_engine(model, cfg, args, paged=paged,
                                 prefix_cache=prefix, spec=spec)
        reports.append(rep)
    return reports, engine


def _print_report(out):
    s = out["serving"]
    tput = out["throughput_tok_s"]
    print(f"serve_bench[{out['mode']}]: {out['completed']}/"
          f"{out['requests']} requests at {out['rate_req_s']} req/s "
          f"({out['length_dist']}) -> "
          f"{'n/a' if tput is None else tput} tok/s over {out['wall_s']}s")
    for name in ("ttft_seconds", "tpot_seconds", "e2e_seconds",
                 "queue_seconds"):
        h = s.get(name)
        if h:
            print(f"  {name:<14} p50 {h['p50'] * 1e3:8.2f} ms   "
                  f"p90 {h['p90'] * 1e3:8.2f} ms   "
                  f"p99 {h['p99'] * 1e3:8.2f} ms")
    fill, kv = s["batch_fill_ratio"], out["kv_occupancy_peak"]
    print(f"  batch fill {'n/a' if fill is None else f'{fill:.2f}'}   "
          f"true kv occupancy (peak) {kv:.2f}   "
          f"batches {s['batches_total']}")
    slo = out["slo"]
    if slo["ttft_attainment"] is not None:
        print(f"  SLO: TTFT<= {slo['ttft_ms']:.0f}ms "
              f"{slo['ttft_attainment'] * 100:.1f}%   "
              f"e2e<= {slo['e2e_ms']:.0f}ms "
              f"{slo['e2e_attainment'] * 100:.1f}%")
    pre = out.get("prefix")
    if pre:
        print(f"  prefix cache {'on ' if pre['enabled'] else 'off'}: "
              f"hit rate {pre['hit_rate'] * 100:.1f}% "
              f"({pre['hits']}/{pre['hits'] + pre['misses']})   "
              f"prefill tokens saved {pre['prefill_tokens_saved']}")
    sp = out.get("spec")
    if sp:
        rate = sp["accept_rate"]
        print(f"  speculative: {sp['windows']} windows, accepted "
              f"{sp['accepted']}/{sp['proposed']} drafts "
              f"({'n/a' if rate is None else f'{rate * 100:.1f}%'})   "
              f"trie {sp['drafts_trie']} / model {sp['drafts_model']}")
    print(f"  steady-state recompiles: {out['steady_recompiles']}")


def _print_spec_comparison(off, on):
    print("\nspeculative decode off vs on (same traffic):")
    print(f"  {'mode':<18} {'tok/s':>10} {'accept rate':>12} "
          f"{'windows':>8}")
    for rep in (off, on):
        sp = rep.get("spec")
        acc = "n/a" if not sp or sp["accept_rate"] is None \
            else f"{sp['accept_rate'] * 100:.1f}%"
        print(f"  {rep['mode']:<18} {str(rep['throughput_tok_s']):>10} "
              f"{acc:>12} {sp['windows'] if sp else 0:>8}")
    if off["throughput_tok_s"] and on["throughput_tok_s"]:
        print(f"  speculative speedup: "
              f"{on['throughput_tok_s'] / off['throughput_tok_s']:.2f}x")


def _print_prefix_comparison(off, on):
    def ttft(rep, q):
        h = rep["serving"].get("ttft_seconds")
        return f"{h[q] * 1e3:10.2f}" if h else "       n/a"

    print("\nprefix cache off vs on (same shared-prefix traffic):")
    print(f"  {'mode':<14} {'tok/s':>10} {'p50 TTFT ms':>12} "
          f"{'p99 TTFT ms':>12} {'hit rate':>9} {'saved tok':>10}")
    for rep in (off, on):
        pre = rep["prefix"]
        print(f"  {rep['mode']:<14} {str(rep['throughput_tok_s']):>10} "
              f"{ttft(rep, 'p50'):>12} {ttft(rep, 'p99'):>12} "
              f"{pre['hit_rate'] * 100:>8.1f}% "
              f"{pre['prefill_tokens_saved']:>10}")


def _print_comparison(padded, paged):
    def p99(rep):
        h = rep["serving"].get("ttft_seconds")
        return f"{h['p99'] * 1e3:10.2f}" if h else "       n/a"

    print("\npadded vs paged (same traffic):")
    print(f"  {'mode':<8} {'tok/s':>10} {'p99 TTFT ms':>12} "
          f"{'true KV occ':>12}")
    for rep in (padded, paged):
        print(f"  {rep['mode']:<8} {str(rep['throughput_tok_s']):>10} "
              f"{p99(rep):>12} {rep['kv_occupancy_peak']:>12.2f}")
    if padded["throughput_tok_s"] and paged["throughput_tok_s"]:
        print(f"  paged speedup: "
              f"{paged['throughput_tok_s'] / padded['throughput_tok_s']:.2f}x")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--preset", default=None,
                    help="gpt3-125m … gpt3-13b (default: 2-layer toy)")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, requests/s")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--prompt-cap", type=int, default=16)
    ap.add_argument("--new", type=int, default=8,
                    help="max new tokens per request")
    ap.add_argument("--decode-chunk", type=int, default=None)
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--int8-weights", action="store_true")
    ap.add_argument("--int8-cache", action="store_true",
                    help="int8 KV cache (padded engine AND the paged "
                         "int8 pool)")
    ap.add_argument("--paged", action="store_true",
                    help="block-pool KV + slot-level continuous batching")
    ap.add_argument("--kv-block", type=int, default=16,
                    help="KV rows per pool block (paged)")
    ap.add_argument("--kv-blocks", type=int, default=None,
                    help="total pool blocks incl. trash (paged; default "
                         "= worst case for the batch)")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="radix-trie prefix cache over the paged pool")
    ap.add_argument("--prefix-cache-bytes", type=int, default=None,
                    help="LRU byte budget for cached prefixes")
    ap.add_argument("--shared-prefix", type=int, default=0, metavar="N",
                    help="replay N system prompts x Poisson suffixes "
                         "through the paged engine with the prefix cache "
                         "off AND on; prints hit rate + TTFT table")
    ap.add_argument("--prefix-len", type=int, default=None,
                    help="system-prompt length for --shared-prefix "
                         "(default: half the prompt cap)")
    ap.add_argument("--spec", action="store_true",
                    help="replay through the paged+prefix engine with "
                         "speculative decode off AND on; prints the "
                         "acceptance table")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="draft tokens per speculative verify window")
    ap.add_argument("--repeat", type=int, default=0, metavar="N",
                    help="workload = N fixed prompts repeated verbatim "
                         "(the agentic/retry shape trie drafting wants)")
    ap.add_argument("--shards", type=int, default=None, metavar="N",
                    help="tensor-parallel shards for the paged engine "
                         "(ISSUE 16): head-shard the KV pools and run "
                         "prefill/decode over an N-chip mp mesh (CPU "
                         "hosts get a virtual mesh via XLA_FLAGS "
                         "automatically)")
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="cap per-step prefill work at [1, N] tokens "
                         "(chunked prefill)")
    ap.add_argument("--length-dist", choices=("uniform", "longtail"),
                    default="uniform",
                    help="prompt-length mix; longtail = Pareto-shaped "
                         "mostly-short traffic")
    ap.add_argument("--compare", action="store_true",
                    help="replay the same traffic padded AND paged, "
                         "print the comparison table")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0)
    ap.add_argument("--slo-e2e-ms", type=float, default=5000.0)
    ap.add_argument("--slo", default=None, metavar="SPEC",
                    help="declarative SLO gate, e.g. "
                         "'ttft_p99=500ms,e2e_p99=2s,goodput=0.95': "
                         "prints the burn-rate table and exits nonzero "
                         "on breach (obs.slo; judges the last engine "
                         "run)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--metrics", action="store_true",
                    help="also dump the Prometheus /metrics payload "
                         "(last engine run)")
    args = ap.parse_args(argv)
    if args.prefix_len is None:
        args.prefix_len = max(1, args.prompt_cap // 2)

    # --shards needs a multi-device backend. XLA reads XLA_FLAGS at first
    # BACKEND INIT (not at jax import), so setting it here still works —
    # only an already-initialized smaller backend is unrecoverable.
    if args.shards and args.shards > 1 \
            and not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count"
                  f"={max(8, args.shards)}")
        if len(jax.devices()) < args.shards:
            print(f"serve_bench: jax initialized with "
                  f"{len(jax.devices())} device(s); --shards "
                  f"{args.shards} needs at least that many (set "
                  f"XLA_FLAGS=--xla_force_host_platform_device_count=N "
                  f"before the first jax backend use)", file=sys.stderr)
            return 2

    try:
        reports, engine = run_bench(args)
    except Exception as e:
        # structured config-validation finding (analysis schema): print
        # WHY the configuration cannot be served, not just that it failed
        finding = getattr(e, "finding", None)
        if finding is None:
            raise
        from paddle_tpu.analysis import Findings
        print("serve_bench: invalid serving configuration")
        print(Findings([finding]).table())
        return 2
    # the SLO gate evaluates BEFORE any printing so --json stays ONE
    # parseable document (slo_gate rides the last report; the human
    # table prints after the reports)
    slo_rows = None
    if args.slo:
        from paddle_tpu.obs import (evaluate_slo, format_slo_table,
                                    parse_slo)
        try:
            targets = parse_slo(args.slo)
        except ValueError as e:
            print(f"serve_bench: bad --slo spec: {e}", file=sys.stderr)
            return 2
        slo_rows = evaluate_slo(targets, engine.metrics)
        reports[-1]["slo_gate"] = slo_rows
    if args.json:
        print(json.dumps(reports if len(reports) > 1 else reports[0],
                         indent=2))
    else:
        for rep in reports:
            _print_report(rep)
        if len(reports) == 2 and args.spec:
            _print_spec_comparison(reports[0], reports[1])
        elif len(reports) == 2 and args.shared_prefix:
            _print_prefix_comparison(reports[0], reports[1])
        elif len(reports) == 2:
            _print_comparison(reports[0], reports[1])
    if args.metrics:
        print(engine.metrics_text(), end="")
    rc = 0 if all(r["steady_recompiles"] == 0 for r in reports) else 1
    if slo_rows is not None:
        if not args.json:
            print(format_slo_table(
                slo_rows, title=f"serve_bench[{reports[-1]['mode']}]"))
        if not all(r["ok"] for r in slo_rows):
            breached = ", ".join(r["target"] for r in slo_rows
                                 if not r["ok"])
            print(f"serve_bench: SLO BREACH on {breached}",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
