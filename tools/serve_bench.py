#!/usr/bin/env python
"""Serving benchmark — replay open-loop traffic through the ServingEngine
and report latency percentiles + SLO attainment.

The serving analog of bench.py: where bench rows measure training
step-time/MFU, this measures the signals a serving deployment is judged by
(PAPERS.md serving studies): TTFT / per-output-token / end-to-end latency
distributions under load, queue wait, batch fill, KV occupancy, and the
fraction of requests meeting their SLOs. Traffic is OPEN-LOOP (Poisson
arrivals at --rate req/s, scheduled independently of service speed) so
queueing shows up honestly: a single-threaded replayer submits each
request with its SCHEDULED arrival timestamp (`enqueue_at`), then serves
whatever is queued — exactly the accounting a load balancer would see.

    PYTHONPATH=. python tools/serve_bench.py \
        [--preset gpt3-125m] --requests 64 --rate 100 \
        --batch 4 --prompt-cap 16 --new 8 \
        --slo-ttft-ms 500 --slo-e2e-ms 2000 [--json] [--metrics]

Without --preset a 2-layer toy GPT runs on CPU (CI-sized); with a preset
set PADDLE_TPU_EXAMPLE_TPU=1 to run real-chip sizes.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(preset):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM, GPTConfig, gpt_config
    paddle.seed(0)
    if preset:
        cfg = gpt_config(preset)
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=128,
                        intermediate_size=128)
    model = GPTForCausalLM(cfg)
    if os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        model.to(dtype="bfloat16")
    model.eval()
    return model, cfg


def run_bench(args):
    """Returns (report_dict, engine) — the engine rides along for the
    optional --metrics exposition dump."""
    from paddle_tpu.inference import (ServingEngine, ServingConfig,
                                      synthetic_traffic)
    model, cfg = build_model(args.preset)
    sc = ServingConfig(max_batch=args.batch, prompt_cap=args.prompt_cap,
                       max_new_tokens=args.new,
                       decode_chunk=args.decode_chunk,
                       queue_capacity=args.queue_capacity,
                       eos_token_id=args.eos,
                       weight_dtype="int8" if args.int8_weights else None,
                       cache_dtype="int8" if args.int8_cache else None)
    engine = ServingEngine(model, sc)

    # warmup batch: compiles the (prefill + chunk) executables once, so the
    # measured replay is the steady state a long-lived server sits in
    warm = synthetic_traffic(args.batch, prompt_cap=args.prompt_cap,
                             vocab_size=cfg.vocab_size, rate=1e9, seed=1)
    for item in warm:
        engine.submit(item["prompt"])
    engine.drain()
    warm_metrics = type(engine.metrics)()       # fresh aggregates
    engine.metrics = warm_metrics

    traffic = synthetic_traffic(args.requests, prompt_cap=args.prompt_cap,
                                vocab_size=cfg.vocab_size, rate=args.rate,
                                seed=args.seed)
    t0 = engine.clock()
    finished = []
    for item in traffic:
        due = t0 + item["at"]
        wait = due - engine.clock()
        if wait > 0:                   # open loop: arrivals keep schedule
            time.sleep(wait)
        # when serving fell BEHIND the schedule, enqueue_at backdates the
        # queue-wait span to the scheduled arrival — the load-balancer view
        engine.submit(item["prompt"], enqueue_at=due)
        while engine.queue_depth >= args.batch:
            finished.extend(engine.step())
    finished.extend(engine.drain())
    wall = engine.clock() - t0

    done = [r for r in finished if r.status == "done"]
    # timed-out traffic counts as an SLO MISS, not a dropped sample —
    # excluding it would report 100% attainment exactly under overload
    n_expired = sum(1 for r in finished if r.status == "timeout")
    ttfts = [r.trace.ttft_s for r in done if r.trace.ttft_s is not None]
    e2es = [r.trace.e2e_s for r in done if r.trace.e2e_s is not None]

    def attainment(vals, limit_ms):
        denom = len(vals) + n_expired
        if not denom:
            return None
        return sum(1 for t in vals if t * 1e3 <= limit_ms) / denom

    slo = {
        "ttft_ms": args.slo_ttft_ms,
        "e2e_ms": args.slo_e2e_ms,
        "expired": n_expired,
        "ttft_attainment": attainment(ttfts, args.slo_ttft_ms),
        "e2e_attainment": attainment(e2es, args.slo_e2e_ms),
    }
    s = engine.summary()
    out = {"preset": args.preset or "toy", "requests": args.requests,
           "rate_req_s": args.rate, "wall_s": round(wall, 3),
           "completed": len(done),
           "throughput_tok_s": round(s["tokens_out_total"] / wall, 1)
           if wall > 0 else None,
           "slo": slo, "serving": s}
    # the recompiles counter is a pure churn signal: refused requests log
    # their shape delta without feeding it (record_compile count=False)
    out["steady_recompiles"] = engine.monitor.recompiles
    return out, engine


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--preset", default=None,
                    help="gpt3-125m … gpt3-13b (default: 2-layer toy)")
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--rate", type=float, default=100.0,
                    help="open-loop arrival rate, requests/s")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--prompt-cap", type=int, default=16)
    ap.add_argument("--new", type=int, default=8,
                    help="max new tokens per request")
    ap.add_argument("--decode-chunk", type=int, default=None)
    ap.add_argument("--queue-capacity", type=int, default=256)
    ap.add_argument("--eos", type=int, default=None)
    ap.add_argument("--int8-weights", action="store_true")
    ap.add_argument("--int8-cache", action="store_true")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0)
    ap.add_argument("--slo-e2e-ms", type=float, default=5000.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--metrics", action="store_true",
                    help="also dump the Prometheus /metrics payload")
    args = ap.parse_args(argv)

    out, engine = run_bench(args)
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        s = out["serving"]
        tput = out["throughput_tok_s"]
        print(f"serve_bench: {out['completed']}/{out['requests']} requests "
              f"at {out['rate_req_s']} req/s -> "
              f"{'n/a' if tput is None else tput} tok/s "
              f"over {out['wall_s']}s")
        for name in ("ttft_seconds", "tpot_seconds", "e2e_seconds",
                     "queue_seconds"):
            h = s.get(name)
            if h:
                print(f"  {name:<14} p50 {h['p50'] * 1e3:8.2f} ms   "
                      f"p90 {h['p90'] * 1e3:8.2f} ms   "
                      f"p99 {h['p99'] * 1e3:8.2f} ms")
        fill, kv = s["batch_fill_ratio"], s["kv_slot_occupancy"]
        print(f"  batch fill {'n/a' if fill is None else f'{fill:.2f}'}   "
              f"kv occupancy {'n/a' if kv is None else f'{kv:.2f}'}   "
              f"batches {s['batches_total']}")
        slo = out["slo"]
        if slo["ttft_attainment"] is not None:
            print(f"  SLO: TTFT<= {slo['ttft_ms']:.0f}ms "
                  f"{slo['ttft_attainment'] * 100:.1f}%   "
                  f"e2e<= {slo['e2e_ms']:.0f}ms "
                  f"{slo['e2e_attainment'] * 100:.1f}%")
        print(f"  steady-state recompiles: {out['steady_recompiles']}")
    if args.metrics:
        print(engine.metrics_text(), end="")
    return 0 if out["steady_recompiles"] == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
