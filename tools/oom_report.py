#!/usr/bin/env python
"""Render an HBM-ledger OOM post-mortem artifact (ISSUE 18).

`MemoryLedger.post_mortem()` writes one JSONL file per device
allocation failure — the head row names the error and the largest
owner, then the full owner census at the moment of failure, then the
last N owner-delta rows (the growth curve). This tool turns that
artifact back into the triage page:

    PYTHONPATH=. python tools/oom_report.py oom_postmortem/oom_*.jsonl
    PYTHONPATH=. python tools/oom_report.py --json path/to/oom.jsonl

With several paths (or a directory) the newest artifact renders last,
so the terminal ends on the most recent failure. Exit 0 = rendered;
2 = no readable artifact among the arguments.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _expand(paths):
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(os.path.join(p, n) for n in sorted(os.listdir(p))
                       if n.endswith(".jsonl"))
        else:
            out.append(p)
    return sorted(out, key=lambda p: (os.path.getmtime(p)
                                      if os.path.exists(p) else 0.0))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("paths", nargs="+",
                    help="post-mortem JSONL artifact(s) or a directory "
                         "of them")
    ap.add_argument("--json", action="store_true",
                    help="emit the parsed artifact(s) as JSON instead "
                         "of the rendered table")
    args = ap.parse_args(argv)

    from paddle_tpu.obs.memz import load_postmortem, render_report

    rendered = 0
    for path in _expand(args.paths):
        try:
            if args.json:
                print(json.dumps(load_postmortem(path), indent=2))
            else:
                print(f"== {path}")
                print(render_report(path))
                print()
            rendered += 1
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"oom_report: skipping {path}: {e}", file=sys.stderr)
    if not rendered:
        print("oom_report: no readable post-mortem artifact",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
