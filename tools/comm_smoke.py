#!/usr/bin/env python
"""comm_smoke — the tier-1 quantized-gradient-sync gate (ISSUE 20).

Two processes (the r16 straggler-harness shape: each process runs the
same single-controller SPMD program on its own 2-device host-platform
CPU mesh) each run a toy-GPT ``TrainStep(grad_comm="int8")`` and prove,
per process:

  1. CommPlan compliance — the step's static collective inventory
     satisfies ``train_comm_plan`` (s8 per-layer-group all-reduces
     present, every f32 all-reduce under the side-channel byte cap);
  2. bit-repeatable loss under a fixed seed — the run is snapshotted
     (params + opt state + RNG), replayed, and the two loss streams must
     be BIT-identical (quantized sync must not introduce nondeterminism);
  3. zero steady-state recompiles — the replay adds no jit cache miss.

The parent then asserts the SHARDS agree: both processes' loss streams
must be bit-identical to each other (replicas of one SPMD program).

Exit 0 = all gates hold; 1 = any violation (the violating worker's
output is printed).
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

STEPS = 3


def worker() -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    import jax  # noqa: F401  (env already pinned by main())
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    from paddle_tpu.jit.api import compile_cache_misses
    from paddle_tpu.analysis import train_comm_plan
    import paddle_tpu.distributed as dist

    shard, world = dist.shard_identity()
    assert world == 2, f"expected a 2-process harness, got world={world}"
    mesh = dist.build_mesh({"dp": 2})
    dist.set_mesh(mesh)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=128, param_dtype="float32")
    model = GPTForCausalLM(cfg)
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-3)
    ts = TrainStep(model, o, lambda ids, lab: model.loss(ids, lab),
                   mesh=mesh, grad_comm="int8")

    rng = np.random.RandomState(0)
    ids = rng.randint(1, 128, (4, 16)).astype("int64")

    # gate 1: CommPlan compliance on the very executable that will run
    plan = train_comm_plan(len(ts._comm_groups), dtype="int8")
    audit = ts.sharding_audit(ids, ids, plan=plan)
    plan_findings = [str(f) for f in audit.findings.for_pass("comm_plan")]
    if plan_findings:
        print(json.dumps({"shard": shard, "ok": False,
                          "plan_findings": plan_findings}))
        return 1

    # materialize opt state BEFORE the snapshot so the replay restores it
    ts._opt_state = ts._init_opt_state()
    ts._apply_param_shardings()
    snap = ts.state_dict()

    def run():
        paddle.seed(123)            # pins the per-step dropout/SR keys
        return [float(ts(ids, ids)) for _ in range(STEPS)]

    losses1 = run()                 # first call compiles (the one miss)
    miss0 = compile_cache_misses()
    ts.set_state_dict(snap)
    losses2 = run()                 # gate 3: replay must not recompile
    steady_misses = compile_cache_misses() - miss0

    ok = losses1 == losses2 and steady_misses == 0
    print(json.dumps({"shard": shard, "ok": ok, "losses": losses1,
                      "replay": losses2, "steady_misses": steady_misses,
                      "n_groups": len(ts._comm_groups)}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as one shard process")
    args = ap.parse_args(argv)
    if args.worker:
        return worker()

    here = os.path.abspath(__file__)
    procs = []
    for shard in range(2):
        env = dict(os.environ,
                   PADDLE_TPU_PROCESS_ID=str(shard),
                   PADDLE_TPU_NUM_PROCESSES="2",
                   JAX_PLATFORMS="cpu",
                   XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                              + " --xla_force_host_platform_device_count=2"))
        env.pop("PADDLE_TPU_TIER_DURATIONS", None)
        procs.append(subprocess.Popen(
            [sys.executable, here, "--worker"], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results, rc = [], 0
    for p in procs:
        out, err = p.communicate(timeout=420)
        if p.returncode != 0:
            print(f"comm_smoke: worker failed (exit {p.returncode}):\n"
                  f"{out}\n{err}", file=sys.stderr)
            rc = 1
            continue
        row = json.loads(out.strip().splitlines()[-1])
        results.append(row)
        print(f"comm_smoke: shard {row['shard']}: losses {row['losses']} "
              f"steady_misses {row['steady_misses']}")
    if rc:
        return rc
    # cross-process agreement: replicas of one SPMD program must see the
    # same loss bit-for-bit
    streams = {json.dumps(r["losses"]) for r in results}
    if len(streams) != 1:
        print(f"comm_smoke: shard loss streams DISAGREE: {streams}",
              file=sys.stderr)
        return 1
    print(f"comm_smoke: PASS — plan compliant, loss bit-repeatable "
          f"across replay and across {len(results)} processes, "
          f"zero steady recompiles")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
