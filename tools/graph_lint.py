#!/usr/bin/env python
"""Audit the framework's standard executables with the static-analysis
suite (paddle_tpu.analysis) and print a findings table.

Targets (--all = every one):

  gpt-static   the padded serving engine's {prefill_static, decode_static}
               executables, captured from a real warmup batch (bf16 model:
               the serving dtype story the dtype-promotion pass audits)
  gpt-paged    the paged engine's {prefill_paged, decode_paged} pair —
               donated block pools cross-checked against the lowered
               modules' input_output_alias tables
  gpt-paged-int8  the int8 paged engine WITH the prefix cache: the int8
               {prefill, decode} pair plus the suffix-prefill and COW
               executables (warmup traffic repeats + diverges a prompt
               so every admission path lowers)
  gpt-paged-spec  the SPECULATIVE engine (ISSUE 11): prefix cache + trie
               drafting, so the [B, k] verify executable lowers alongside
               prefill / decode / COW / suffix-prefill — donation and
               host-transfer audited over the whole spec set, and the
               run asserts the steady loop added zero jit cache misses
               (the zero-recompile invariant, proven not claimed)
  train-step   TrainStep(gpt) — traced abstractly (never executed):
               host-transfer / dtype / baked-const / donation over the
               fused fwd+bwd+optimizer step
  resnet50     the vision forward executable (+ its TrainStep with
               --vision-train), channels-last flag as configured

Exit status: 0 = clean (allowlisted findings are clean — each carries its
documented reason), 1 = active findings at/above --fail-on, 2 = bad usage.

    python tools/graph_lint.py --all
    python tools/graph_lint.py --target gpt-paged --json
    python tools/graph_lint.py --all --fail-on error --allow my_allow.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TARGETS = ("gpt-static", "gpt-paged", "gpt-paged-int8", "gpt-paged-spec",
           "train-step", "resnet50")


def _tiny_gpt(dtype="bfloat16"):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    intermediate_size=128, param_dtype=dtype)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


def audit_gpt_engine(lint, *, paged: bool, int8: bool = False,
                     prefix: bool = False, spec: bool = False):
    """Serve one warmup batch through the real engine with lint enabled;
    the engine captures + audits its executables itself. With `prefix`
    the traffic repeats a block-aligned prompt (COW executable) and
    diverges from it mid-prefix (suffix-prefill executable), so the
    whole prefix-cache executable set lowers and is audited. With `spec`
    (ISSUE 11) the repeated prompt's decode drafts the first run's
    cached chain from the trie, so the [B, k] verify executable lowers
    too — and the target additionally PROVES the zero-recompile
    invariant: a steady spec loop after warmup must add zero jit cache
    misses."""
    import numpy as np
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    cfg = ServingConfig(max_batch=2, prompt_cap=8, max_new_tokens=6,
                        decode_chunk=2, eos_token_id=None, paged=paged,
                        kv_block=4, lint=lint,
                        cache_dtype="int8" if int8 else None,
                        prefix_cache=prefix,
                        kv_blocks=65 if spec else
                        (33 if prefix else None),
                        spec_decode=spec)
    eng = ServingEngine(model, cfg)
    rng = np.random.RandomState(0)
    eng.submit(rng.randint(1, 100, (5,)))
    eng.submit(rng.randint(1, 100, (8,)))
    eng.drain()
    if prefix:
        # the shared warmup choreography: aligned miss + COW repeat +
        # mid-prefix divergence, so every admission executable lowers
        eng.warmup_prefix_cache(100, clear=False)
    if spec:
        from paddle_tpu.jit.api import compile_cache_misses
        miss0 = compile_cache_misses()
        for _ in range(2):                 # steady repeats: trie-drafted
            eng.submit(rng.randint(1, 100, (8,)))
            eng.drain()
        p = rng.randint(1, 100, (8,))
        for _ in range(2):
            eng.submit(p)
            eng.drain()
        dm = compile_cache_misses() - miss0
        if dm:
            raise SystemExit(f"gpt-paged-spec: steady speculative loop "
                             f"added {dm} jit cache miss(es) — the "
                             f"zero-recompile invariant is broken")
        if eng.metrics.counters["spec_windows"] < 1:
            # not an assert: under python -O it would vanish and the
            # target would silently audit only the non-spec executables
            raise SystemExit("gpt-paged-spec: warmup never ran a verify "
                             "window — the speculative executable was "
                             "never lowered, nothing was audited")
    return eng.lint_findings


def audit_train_step(lint):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    model, cfg = _tiny_gpt()
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)

    def loss_fn(ids, labels):
        return model.loss(ids, labels)

    ts = TrainStep(model, o, loss_fn)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)))
    return ts.lint(ids, ids, lint=lint)


def audit_resnet50(lint, train: bool = False):
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import autograd
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.api import _swap_params, _trace_guard
    from paddle_tpu.vision.models.resnet import resnet50
    paddle.seed(0)
    model = resnet50()
    model.eval()
    params = [p for _, p in model.named_parameters()]
    buffers = [b for _, b in model.named_buffers()]

    def fwd(pa, ba, x):
        with _trace_guard(), _swap_params(params + buffers,
                                          list(pa) + list(ba)), \
                autograd.no_grad():
            return model(Tensor(x))._data

    sds = lambda t: jax.ShapeDtypeStruct(tuple(t.shape), t.dtype)  # noqa
    findings = lint.check(
        fwd, tuple(sds(p._data) for p in params),
        tuple(sds(b._data) for b in buffers),
        jax.ShapeDtypeStruct((2, 3, 224, 224), "float32"),
        name="resnet50_forward")
    if train:
        from paddle_tpu import optimizer as opt, nn
        from paddle_tpu.jit.train_step import TrainStep
        model.train()
        o = opt.Momentum(parameters=model.parameters(), learning_rate=0.1)
        ce = nn.CrossEntropyLoss()

        def loss_fn(x, y):
            return ce(model(x), y)

        ts = TrainStep(model, o, loss_fn)
        x = jax.ShapeDtypeStruct((2, 3, 224, 224), "float32")
        y = jax.ShapeDtypeStruct((2,), "int64")
        findings.extend(ts.lint(x, y, lint=lint))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--all", action="store_true",
                    help="audit every target")
    ap.add_argument("--target", choices=TARGETS, action="append",
                    default=None)
    ap.add_argument("--fail-on", choices=("info", "warn", "error"),
                    default="warn",
                    help="exit 1 when a non-allowlisted finding at/above "
                         "this severity survives (default warn)")
    ap.add_argument("--allow", default=None,
                    help="JSON allowlist file (list of entry dicts) "
                         "appended to the built-in allowlist")
    ap.add_argument("--vision-train", action="store_true",
                    help="also audit TrainStep(resnet50) — slower trace")
    # thresholds default LOW: the audited models are CPU-sized toys, and
    # the point is to see every site — deliberate ones arrive allowlisted
    # with their documented reason, so low thresholds still exit 0
    ap.add_argument("--upcast-bytes", type=int, default=256)
    ap.add_argument("--const-bytes", type=int, default=1 << 16)
    ap.add_argument("--donate-bytes", type=int, default=1 << 16)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    targets = list(TARGETS) if args.all or not args.target else args.target

    from paddle_tpu.analysis import Allowlist, Findings, GraphLint
    extra = Allowlist.from_json(args.allow).entries if args.allow else None
    lint = GraphLint(allow=extra, upcast_bytes=args.upcast_bytes,
                     const_bytes=args.const_bytes,
                     donate_bytes=args.donate_bytes)

    runners = {
        "gpt-static": lambda: audit_gpt_engine(lint, paged=False),
        "gpt-paged": lambda: audit_gpt_engine(lint, paged=True),
        "gpt-paged-int8": lambda: audit_gpt_engine(lint, paged=True,
                                                   int8=True, prefix=True),
        "gpt-paged-spec": lambda: audit_gpt_engine(lint, paged=True,
                                                   prefix=True, spec=True),
        "train-step": lambda: audit_train_step(lint),
        "resnet50": lambda: audit_resnet50(lint,
                                           train=args.vision_train),
    }

    all_findings = Findings()
    report = {}
    for t in targets:
        t0 = time.perf_counter()
        findings = runners[t]() or Findings()
        dt = time.perf_counter() - t0
        report[t] = {"seconds": round(dt, 1),
                     "findings": findings.to_dicts()}
        all_findings.extend(findings)
        if not args.json:
            print(findings.grouped().table(f"{t} ({dt:.1f}s):"))

    active = all_findings.active(args.fail_on)
    if args.json:
        report["active"] = len(active)
        print(json.dumps(report, indent=2))
    else:
        n_allowed = sum(1 for f in all_findings if f.allowed)
        print(f"\ngraph_lint: {len(all_findings)} finding(s), "
              f"{n_allowed} allowlisted, {len(active)} active "
              f"(fail-on {args.fail_on})")
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
