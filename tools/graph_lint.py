#!/usr/bin/env python
"""Audit the framework's standard executables with the static-analysis
suite (paddle_tpu.analysis) and print a findings table.

Targets (--all = every one):

  gpt-static   the padded serving engine's {prefill_static, decode_static}
               executables, captured from a real warmup batch (bf16 model:
               the serving dtype story the dtype-promotion pass audits)
  gpt-paged    the paged engine's {prefill_paged, decode_paged} pair —
               donated block pools cross-checked against the lowered
               modules' input_output_alias tables
  gpt-paged-int8  the int8 paged engine WITH the prefix cache: the int8
               {prefill, decode} pair plus the suffix-prefill and COW
               executables (warmup traffic repeats + diverges a prompt
               so every admission path lowers)
  gpt-paged-spec  the SPECULATIVE engine (ISSUE 11): prefix cache + trie
               drafting, so the [B, k] verify executable lowers alongside
               prefill / decode / COW / suffix-prefill — donation and
               host-transfer audited over the whole spec set, and the
               run asserts the steady loop added zero jit cache misses
               (the zero-recompile invariant, proven not claimed)
  train-step   TrainStep(gpt) — traced abstractly (never executed):
               host-transfer / dtype / baked-const / donation over the
               fused fwd+bwd+optimizer step
  resnet50     the vision forward executable (+ its TrainStep with
               --vision-train), channels-last flag as configured

Sharded targets (ISSUE 15 — run on an 8-device host-platform CPU mesh,
XLA_FLAGS=--xla_force_host_platform_device_count=8 is set automatically
when one is requested; nothing executes, the step is lowered + compiled
and its post-SPMD HLO statically audited):

  train-step-dp   TrainStep(gpt) on a {"dp": 8} mesh. Declared CommPlan:
                  all-reduce only (grad sync + loss reductions) — ANY
                  other collective kind is a partitioner-inserted
                  resharding and fails the plan check. Plus the full
                  abstract pass suite and the resharding/replication
                  sharding passes.
  train-step-tp   the same step on a {"dp": 2, "mp": 4} hybrid mesh.
                  CommPlan: all-reduce + all-gather (TP activation
                  traffic); the vocab-parallel table gather arrives
                  allowlisted with its documented reason.
  comm-xcheck     static-vs-runtime bytes cross-check: compile the
                  mini-step twin of the checked-in trace fixture
                  (tests/fixtures/mini_step.trace.json.gz) and assert
                  the static collective-bytes table matches the runtime
                  trace-ledger bytes per collective kind within
                  --xcheck-rtol (default 1%).
  gpt-paged-sharded  the MULTI-CHIP paged engine (ISSUE 16): serve a real
                  warmup batch at --shards (default 4) on the host-
                  platform mesh, then statically prove the whole paged
                  executable set — the abstract pass suite (pool donation
                  included), a zero-steady-state-recompile loop, and the
                  compiled-HLO sharding audit of every executable against
                  the DECLARED serving CommPlan: model executables are
                  exactly 2*num_layers mp-group all-reduces (one per
                  row-parallel matmul), the COW copy is zero collectives
                  (shard-local by plan). A partitioner-inserted KV
                  gather/resharding fails the plan check with the op
                  named.

--plant-reshard is a self-test of the detector: it gives one layer's
weight a deliberately wrong pspec on the sharded train-step targets and
INVERTS the expectation — exit 0 only if the planted resharding is
detected and named, 1 if the lint missed it.

Exit status: 0 = clean (allowlisted findings are clean — each carries its
documented reason; with --plant-reshard: the planted resharding was
detected), 1 = active findings at/above --fail-on (comm-plan violations
and a failed comm-xcheck land here; with --plant-reshard: the planted
resharding was MISSED), 2 = bad usage.

    python tools/graph_lint.py --all
    python tools/graph_lint.py train-step-dp train-step-tp comm-xcheck
    python tools/graph_lint.py --target gpt-paged --json
    python tools/graph_lint.py --all --fail-on error --allow my_allow.json
    python tools/graph_lint.py train-step-dp --plant-reshard
"""
from __future__ import annotations

import argparse
import copy
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

TARGETS = ("gpt-static", "gpt-paged", "gpt-paged-int8", "gpt-paged-spec",
           "train-step", "resnet50",
           "train-step-dp", "train-step-tp", "train-step-int8",
           "comm-xcheck", "gpt-paged-sharded")
#: targets that need the multi-device host-platform mesh
SHARDED_TARGETS = ("train-step-dp", "train-step-tp", "train-step-int8",
                   "comm-xcheck", "gpt-paged-sharded")

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures",
    "mini_step.trace.json.gz")


def _tiny_gpt(dtype="bfloat16"):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=128,
                    intermediate_size=128, param_dtype=dtype)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


def audit_gpt_engine(lint, *, paged: bool, int8: bool = False,
                     prefix: bool = False, spec: bool = False):
    """Serve one warmup batch through the real engine with lint enabled;
    the engine captures + audits its executables itself. With `prefix`
    the traffic repeats a block-aligned prompt (COW executable) and
    diverges from it mid-prefix (suffix-prefill executable), so the
    whole prefix-cache executable set lowers and is audited. With `spec`
    (ISSUE 11) the repeated prompt's decode drafts the first run's
    cached chain from the trie, so the [B, k] verify executable lowers
    too — and the target additionally PROVES the zero-recompile
    invariant: a steady spec loop after warmup must add zero jit cache
    misses."""
    import numpy as np
    from paddle_tpu.inference import ServingConfig, ServingEngine
    model, _ = _tiny_gpt()
    cfg = ServingConfig(max_batch=2, prompt_cap=8, max_new_tokens=6,
                        decode_chunk=2, eos_token_id=None, paged=paged,
                        kv_block=4, lint=lint,
                        cache_dtype="int8" if int8 else None,
                        prefix_cache=prefix,
                        kv_blocks=65 if spec else
                        (33 if prefix else None),
                        spec_decode=spec)
    eng = ServingEngine(model, cfg)
    rng = np.random.RandomState(0)
    eng.submit(rng.randint(1, 100, (5,)))
    eng.submit(rng.randint(1, 100, (8,)))
    eng.drain()
    if prefix:
        # the shared warmup choreography: aligned miss + COW repeat +
        # mid-prefix divergence, so every admission executable lowers
        eng.warmup_prefix_cache(100, clear=False)
    if spec:
        from paddle_tpu.jit.api import compile_cache_misses
        miss0 = compile_cache_misses()
        for _ in range(2):                 # steady repeats: trie-drafted
            eng.submit(rng.randint(1, 100, (8,)))
            eng.drain()
        p = rng.randint(1, 100, (8,))
        for _ in range(2):
            eng.submit(p)
            eng.drain()
        dm = compile_cache_misses() - miss0
        if dm:
            raise SystemExit(f"gpt-paged-spec: steady speculative loop "
                             f"added {dm} jit cache miss(es) — the "
                             f"zero-recompile invariant is broken")
        if eng.metrics.counters["spec_windows"] < 1:
            # not an assert: under python -O it would vanish and the
            # target would silently audit only the non-spec executables
            raise SystemExit("gpt-paged-spec: warmup never ran a verify "
                             "window — the speculative executable was "
                             "never lowered, nothing was audited")
    return eng.lint_findings


def audit_gpt_engine_sharded(lint, shards: int = 4, audits=None):
    """Multi-chip sharded serving audit (ISSUE 16): run a real warmup
    batch through a head-sharded paged engine on the host-platform mesh,
    then prove the plan statically —

      1. abstract pass suite over every captured executable (host
         transfer, dtype, baked consts, POOL DONATION via the
         input_output_alias cross-check);
      2. zero steady-state recompiles: post-warmup traffic at the same
         shard count must add zero jit cache misses;
      3. compiled-HLO sharding audit of each executable under the mesh
         against the DECLARED serving CommPlan
         (analysis.commplan.serving_comm_plan): prefill/decode/verify
         are EXACTLY 2*num_layers mp-group all-reduces (the row-parallel
         matmuls) and nothing else; the COW block copy is ZERO
         collectives (shard-locality, proven not claimed). Any
         partitioner-inserted KV gather shows up as comm_extra with the
         op named and fails the run.
    """
    import numpy as np
    from paddle_tpu.analysis import Findings, lint_capture
    from paddle_tpu.analysis.commplan import serving_comm_plan
    from paddle_tpu.analysis.lint import _kind_name
    from paddle_tpu.inference import ServingConfig, ServingEngine
    from paddle_tpu.jit.api import compile_cache_misses
    model, mcfg = _tiny_gpt()
    cfg = ServingConfig(max_batch=2, prompt_cap=8, max_new_tokens=6,
                        decode_chunk=2, eos_token_id=None, paged=True,
                        kv_block=4, shards=shards)
    eng = ServingEngine(model, cfg)
    rng = np.random.RandomState(0)
    with lint_capture() as calls:
        eng.submit(rng.randint(1, 100, (5,)))
        eng.submit(rng.randint(1, 100, (8,)))
        eng.drain()
    if not calls:
        raise SystemExit("gpt-paged-sharded: warmup captured no "
                         "executables — nothing was audited")

    # zero steady-state recompiles at this shard count
    miss0 = compile_cache_misses()
    for _ in range(2):
        eng.submit(rng.randint(1, 100, (7,)))
        eng.drain()
    dm = compile_cache_misses() - miss0
    if dm:
        raise SystemExit(f"gpt-paged-sharded: steady sharded loop added "
                         f"{dm} jit cache miss(es) — a shard-dependent "
                         f"signature component is missing")

    # abstract passes (donation included) over the captured set
    findings = lint.check_calls(calls, guard=False)

    # compiled-HLO sharding audit per unique executable, under the
    # engine's mesh, against the declared serving plan
    model_plan = serving_comm_plan(mcfg.num_layers)
    local_plan = serving_comm_plan(0)     # COW copy: zero collectives
    seen, audited = set(), set()
    with eng._mesh_scope():
        for kind, fn, (args, kwargs) in calls:
            head = kind[0] if isinstance(kind, tuple) else str(kind)
            if not str(head).startswith("paged_"):
                continue
            name = _kind_name(kind)
            if (id(fn), name) in seen:
                continue
            seen.add((id(fn), name))
            plan = local_plan if head == "paged_cow" else model_plan
            audit = lint.check_sharded(fn, *args, name=name, plan=plan,
                                       mesh_axes={"mp": shards},
                                       guard=False, **kwargs)
            findings.extend(audit.findings)
            audited.add(str(head))
            if audits is not None:
                audits[name] = audit
    if "paged_decode" not in audited:
        raise SystemExit("gpt-paged-sharded: the decode executable was "
                         "never captured/audited — the comm-plan gate "
                         "proved nothing")
    return findings


def audit_train_step(lint):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt
    from paddle_tpu.jit.train_step import TrainStep
    model, cfg = _tiny_gpt()
    model.train()
    o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)

    def loss_fn(ids, labels):
        return model.loss(ids, labels)

    ts = TrainStep(model, o, loss_fn)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16)))
    return ts.lint(ids, ids, lint=lint)


def audit_resnet50(lint, train: bool = False):
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core import autograd
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.api import _swap_params, _trace_guard
    from paddle_tpu.vision.models.resnet import resnet50
    paddle.seed(0)
    model = resnet50()
    model.eval()
    params = [p for _, p in model.named_parameters()]
    buffers = [b for _, b in model.named_buffers()]

    def fwd(pa, ba, x):
        with _trace_guard(), _swap_params(params + buffers,
                                          list(pa) + list(ba)), \
                autograd.no_grad():
            return model(Tensor(x))._data

    sds = lambda t: jax.ShapeDtypeStruct(tuple(t.shape), t.dtype)  # noqa
    findings = lint.check(
        fwd, tuple(sds(p._data) for p in params),
        tuple(sds(b._data) for b in buffers),
        jax.ShapeDtypeStruct((2, 3, 224, 224), "float32"),
        name="resnet50_forward")
    if train:
        from paddle_tpu import optimizer as opt, nn
        from paddle_tpu.jit.train_step import TrainStep
        model.train()
        o = opt.Momentum(parameters=model.parameters(), learning_rate=0.1)
        ce = nn.CrossEntropyLoss()

        def loss_fn(x, y):
            return ce(model(x), y)

        ts = TrainStep(model, o, loss_fn)
        x = jax.ShapeDtypeStruct((2, 3, 224, 224), "float32")
        y = jax.ShapeDtypeStruct((2,), "int64")
        findings.extend(ts.lint(x, y, lint=lint))
    return findings


def audit_train_step_sharded(lint, axes, plan=None, plant=False,
                             audits=None):
    """Sharded train-step audit (ISSUE 15): TrainStep(gpt) under a mesh,
    audited end-to-end through TrainStep.lint — the abstract pass suite
    PLUS the compiled-HLO sharding passes and the target's CommPlan.
    With `plant`, one layer's weight gets a deliberately wrong pspec and
    the run asserts the resharding is detected and NAMED (the detector's
    self-test); detection inverts into a clean exit."""
    import numpy as np
    import jax
    from jax.sharding import PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt
    from paddle_tpu.analysis import Findings
    from paddle_tpu.jit.train_step import TrainStep
    import paddle_tpu.distributed as dist
    mesh = dist.build_mesh(axes)
    dist.set_mesh(mesh)
    try:
        model, cfg = _tiny_gpt()
        model.train()
        planted = "gpt.h.0.mlp.up.weight"
        if plant:
            model.gpt.h[0].mlp.up.weight.pspec = P("dp", None)
        o = opt.AdamW(parameters=model.parameters(), learning_rate=1e-4)
        ts = TrainStep(model, o, lambda ids, lab: model.loss(ids, lab),
                       mesh=mesh)
        linter = copy.copy(lint)
        linter.comm_plan = None if plant else plan
        ids = jax.ShapeDtypeStruct((8, 16), "int64")
        findings = ts.lint(ids, ids, lint=linter)
        if audits is not None and ts.comm_audit is not None:
            audits[f"train-step-{'x'.join(map(str, axes.values()))}"] = \
                ts.comm_audit
        if plant:
            hits = [f for f in findings if f.code == "param_gather"
                    and planted in (f.where or "")]
            if not hits:
                raise SystemExit(
                    f"--plant-reshard: the planted wrong pspec on "
                    f"{planted} was NOT detected — the resharding pass "
                    f"is blind")
            print(f"  plant-reshard: detected and named — {hits[0]}",
                  file=sys.stderr)
            # detection is the pass criterion; the planted findings must
            # not fail the run
            return Findings()
        return findings
    finally:
        dist.set_mesh(None)


def audit_train_step_int8(lint, audits=None, min_ratio: float = 3.5):
    """Quantized gradient-sync audit (ISSUE 20): the dp=8 tiny-GPT
    TrainStep is built twice — the f32 twin (implicit partitioner psum)
    and ``grad_comm="int8"`` — both statically audited, and two
    invariants gated:

      1. the int8 inventory satisfies ``train_comm_plan`` — the s8
         per-layer-group all-reduces are present and every f32 all-reduce
         stays under the side-channel byte cap (an eighth of the twin's
         gradient-sync bytes): an f32 gradient all-reduce sneaking back
         (fallback-classifier regression, shard_map bypass) fails here;
      2. the static all-reduce bytes-per-step drop >= ``min_ratio`` vs
         the twin (the EQuARX ~4x wire cut, measured on the very HLO that
         will run).
    """
    import jax
    from paddle_tpu import optimizer as opt
    from paddle_tpu.analysis import Finding, Findings, train_comm_plan
    from paddle_tpu.jit.train_step import TrainStep
    import paddle_tpu.distributed as dist
    mesh = dist.build_mesh({"dp": 8})
    dist.set_mesh(mesh)
    try:
        ids = jax.ShapeDtypeStruct((8, 16), "int64")

        def build(mode):
            model, _ = _tiny_gpt("float32")
            model.train()
            o = opt.AdamW(parameters=model.parameters(),
                          learning_rate=1e-4)
            return TrainStep(model, o,
                             lambda i, l: model.loss(i, l),
                             mesh=mesh, grad_comm=mode)

        def ar_bytes(audit):
            return sum(r.get("bytes") or 0 for r in audit.rows
                       if r.get("kind") == "all-reduce")

        twin_audit = build(None).sharding_audit(ids, ids)
        twin_b = ar_bytes(twin_audit)
        ts = build("int8")
        plan = train_comm_plan(len(ts._comm_groups), dtype="int8",
                               max_f32_bytes=max(twin_b // 8, 1))
        linter = copy.copy(lint)
        linter.comm_plan = plan
        audit = ts.sharding_audit(ids, ids, lint=linter)
        findings = Findings()
        findings.extend(audit.findings)
        int8_b = ar_bytes(audit)
        ratio = twin_b / max(int8_b, 1)
        print(f"  train-step-int8: all-reduce bytes/step "
              f"{twin_b} (f32 twin) -> {int8_b} (int8), "
              f"ratio {ratio:.2f}x (gate >= {min_ratio}x)",
              file=sys.stderr)
        if ratio < min_ratio:
            findings.add(Finding(
                "comm_plan", "comm_bytes", "error",
                f"int8 gradient sync moves {int8_b} all-reduce "
                f"bytes/step vs the f32 twin's {twin_b} — only "
                f"{ratio:.2f}x, gate requires >= {min_ratio}x "
                f"(quantized lanes regressed or fallback grew)",
                where="all-reduce", executable="train-step-int8",
                data={"twin_bytes": twin_b, "int8_bytes": int8_b,
                      "ratio": ratio, "min_ratio": min_ratio}))
        if audits is not None and ts.comm_audit is not None:
            audits["train-step-int8"] = ts.comm_audit
        return findings
    finally:
        dist.set_mesh(None)


def audit_comm_xcheck(rtol: float = 0.01, audits=None):
    """Static-vs-runtime cross-check (ISSUE 15 acceptance): compile the
    jitted twin of the checked-in mini-step fixture — one dp=8 grad-sync
    all-reduce moving the fixture's 1 MiB per step — and assert the
    static inventory's bytes match the runtime trace ledger's per-step
    bytes per collective kind within `rtol`. A mismatch is a Finding
    (exit 1), not an assert: the table prints either way."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.analysis import (Finding, Findings,
                                     collective_inventory,
                                     compiled_hlo_text)
    from paddle_tpu.obs.collectives import CollectiveLedger

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    # the twin: a data-parallel partial-sum + all-reduce whose buffer is
    # exactly the fixture's bytes_accessed (f32[131072]: 0.5 MiB operand
    # + 0.5 MiB output = 1 MiB per step)
    jfn = jax.jit(lambda x: jnp.sum(x, axis=0),
                  in_shardings=(NamedSharding(mesh, P("dp", None)),),
                  out_shardings=NamedSharding(mesh, P()))
    text = compiled_hlo_text(
        jfn, jax.ShapeDtypeStruct((8, 131072), jnp.float32))
    rows = collective_inventory(text, "mini_step_twin")
    ledger = CollectiveLedger.from_trace(FIXTURE, steps=2)
    diff = ledger.check_static(rows, rtol=rtol)
    findings = Findings()
    for d in diff:
        rel = f"{d['rel_err'] * 100:.2f}%" if d["rel_err"] is not None \
            else "-"
        if not d["ok"]:
            findings.add(Finding(
                "sharding", "static_runtime_bytes", "error",
                f"{d['kind']}: static {d['static_bytes']} B/step vs "
                f"runtime {d['runtime_bytes']} B/step "
                f"(rel err {rel}, rtol {rtol:.0%}) — the audited "
                f"executable is not the one the trace measured",
                where=d["kind"], executable="comm-xcheck", data=d))
    if audits is not None:
        audits["comm-xcheck"] = {"diff": diff,
                                 "rows": [dict(r) for r in rows]}
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        epilog="Exit status: 0 = clean (allowlisted findings count as "
               "clean; with --plant-reshard: planted resharding "
               "detected), 1 = active findings at/above --fail-on "
               "(comm-plan violations and comm-xcheck byte mismatches "
               "included; with --plant-reshard: detection MISSED), "
               "2 = bad usage.")
    ap.add_argument("targets", nargs="*", metavar="TARGET",
                    help=f"targets to audit (positional form of "
                         f"--target; one of {', '.join(TARGETS)})")
    ap.add_argument("--all", action="store_true",
                    help="audit every target")
    ap.add_argument("--target", choices=TARGETS, action="append",
                    default=None)
    ap.add_argument("--fail-on", choices=("info", "warn", "error"),
                    default="warn",
                    help="exit 1 when a non-allowlisted finding at/above "
                         "this severity survives (default warn)")
    ap.add_argument("--allow", default=None,
                    help="JSON allowlist file (list of entry dicts) "
                         "appended to the built-in allowlist")
    ap.add_argument("--vision-train", action="store_true",
                    help="also audit TrainStep(resnet50) — slower trace")
    # thresholds default LOW: the audited models are CPU-sized toys, and
    # the point is to see every site — deliberate ones arrive allowlisted
    # with their documented reason, so low thresholds still exit 0
    ap.add_argument("--upcast-bytes", type=int, default=256)
    ap.add_argument("--const-bytes", type=int, default=1 << 16)
    ap.add_argument("--donate-bytes", type=int, default=1 << 16)
    # replicated-parameter threshold stays at 1 MiB by default: the toy
    # models' replicated layernorm/bias params are design, not findings
    ap.add_argument("--replicated-bytes", type=int, default=1 << 20)
    ap.add_argument("--plant-reshard", action="store_true",
                    help="self-test: plant a wrong pspec on one layer "
                         "of the sharded train-step targets and require "
                         "the resharding pass to detect + name it")
    ap.add_argument("--xcheck-rtol", type=float, default=0.01,
                    help="comm-xcheck static-vs-runtime bytes tolerance "
                         "(default 1%%)")
    ap.add_argument("--shards", type=int, default=4,
                    help="mp degree for gpt-paged-sharded (default 4; "
                         "must divide the toy model's 4 heads)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report: per-target findings, "
                         "the static comm tables of the sharded targets "
                         "and the comm-xcheck diff, plus the active "
                         "count (exit semantics unchanged)")
    args = ap.parse_args(argv)

    bad = [t for t in args.targets if t not in TARGETS]
    if bad:
        ap.error(f"unknown target(s) {bad} (choose from "
                 f"{', '.join(TARGETS)})")
    # dedupe, first mention wins (a target named both positionally and
    # via --target must not be audited/counted twice)
    targets = list(dict.fromkeys(
        list(args.targets) + list(args.target or [])))
    if args.all or not targets:
        targets = list(TARGETS)
    if args.plant_reshard and not any(
            t in ("train-step-dp", "train-step-tp") for t in targets):
        ap.error("--plant-reshard applies to the sharded train-step "
                 "targets (train-step-dp / train-step-tp)")

    # the sharded targets need the virtual multi-device mesh. XLA reads
    # XLA_FLAGS at first BACKEND INIT (not at jax import), so setting it
    # here still works even when jax was imported earlier — only an
    # already-initialized small backend is unrecoverable.
    if any(t in SHARDED_TARGETS for t in targets):
        if "--xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
        if "jax" in sys.modules:
            try:
                from jax._src import xla_bridge as _xb
                initialized = bool(getattr(_xb, "_backends", None))
            except Exception:
                initialized = True   # can't tell: probe (may init)
            import jax
            if initialized and len(jax.devices()) < 8:
                print("graph_lint: jax already initialized with "
                      f"{len(jax.devices())} device(s); sharded targets "
                      "need 8 (set XLA_FLAGS="
                      "--xla_force_host_platform_device_count=8 before "
                      "the first jax backend use)",
                      file=sys.stderr)
                return 2

    from paddle_tpu.analysis import (Allowlist, CommPlan, Findings,
                                     GraphLint)
    extra = Allowlist.from_json(args.allow).entries if args.allow else None
    lint = GraphLint(allow=extra, upcast_bytes=args.upcast_bytes,
                     const_bytes=args.const_bytes,
                     donate_bytes=args.donate_bytes,
                     replicated_bytes=args.replicated_bytes)

    audits = {}
    # the declared communication plans of the shipped sharded configs:
    # dp trains on grad-sync all-reduces ALONE; the hybrid tp mesh adds
    # the TP activation all-gathers. Anything else = partitioner crept.
    runners = {
        "gpt-static": lambda: audit_gpt_engine(lint, paged=False),
        "gpt-paged": lambda: audit_gpt_engine(lint, paged=True),
        "gpt-paged-int8": lambda: audit_gpt_engine(lint, paged=True,
                                                   int8=True, prefix=True),
        "gpt-paged-spec": lambda: audit_gpt_engine(lint, paged=True,
                                                   prefix=True, spec=True),
        "train-step": lambda: audit_train_step(lint),
        "resnet50": lambda: audit_resnet50(lint,
                                           train=args.vision_train),
        "train-step-dp": lambda: audit_train_step_sharded(
            lint, {"dp": 8}, plan=CommPlan({"all-reduce": "+"}),
            plant=args.plant_reshard, audits=audits),
        "train-step-tp": lambda: audit_train_step_sharded(
            lint, {"dp": 2, "mp": 4},
            plan=CommPlan({"all-reduce": "+", "all-gather": "+"}),
            plant=args.plant_reshard, audits=audits),
        "train-step-int8": lambda: audit_train_step_int8(
            lint, audits=audits),
        "comm-xcheck": lambda: audit_comm_xcheck(
            rtol=args.xcheck_rtol, audits=audits),
        "gpt-paged-sharded": lambda: audit_gpt_engine_sharded(
            lint, shards=args.shards, audits=audits),
    }

    all_findings = Findings()
    report = {}
    for t in targets:
        t0 = time.perf_counter()
        findings = runners[t]() or Findings()
        dt = time.perf_counter() - t0
        report[t] = {"seconds": round(dt, 1),
                     "findings": findings.to_dicts()}
        all_findings.extend(findings)
        if not args.json:
            print(findings.grouped().table(f"{t} ({dt:.1f}s):"))

    if not args.json:
        for key, audit in audits.items():
            if hasattr(audit, "table"):
                print("\n" + audit.table())
            elif isinstance(audit, dict) and "diff" in audit:
                print(f"\n---- Static-vs-runtime bytes ({key}) ----")
                print(f"  {'kind':<20} {'static B/step':>14} "
                      f"{'runtime B/step':>14} {'rel err':>8}")
                for d in audit["diff"]:
                    rel = f"{d['rel_err'] * 100:.2f}%" \
                        if d["rel_err"] is not None else "-"
                    print(f"  {d['kind']:<20} "
                          f"{str(d['static_bytes']):>14} "
                          f"{str(d['runtime_bytes']):>14} {rel:>8}"
                          + ("" if d["ok"] else "  MISMATCH"))

    active = all_findings.active(args.fail_on)
    if args.json:
        report["comm"] = {
            k: (a.to_dict() if hasattr(a, "to_dict") else a)
            for k, a in audits.items()}
        report["active"] = len(active)
        print(json.dumps(report, indent=2))
    else:
        n_allowed = sum(1 for f in all_findings if f.allowed)
        print(f"\ngraph_lint: {len(all_findings)} finding(s), "
              f"{n_allowed} allowlisted, {len(active)} active "
              f"(fail-on {args.fail_on})")
    return 1 if active else 0


if __name__ == "__main__":
    raise SystemExit(main())
