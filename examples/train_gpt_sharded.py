"""Hybrid-parallel GPT training on a device mesh.

On CPU this uses 8 virtual devices (set before jax import); on a TPU slice
the same code uses the real chips. Usage:
    PYTHONPATH=. python examples/train_gpt_sharded.py
"""
import os

if "--xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import jax

# examples default to CPU so they run anywhere; set PADDLE_TPU_EXAMPLE_TPU=1
# on a TPU host to use the chips
if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.jit.train_step import TrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion


def main():
    mesh = dist.build_mesh({"dp": 2, "sdp": 2, "mp": 2})
    dist.set_mesh(mesh)
    paddle.seed(0)

    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=2,
                    num_heads=4, max_position_embeddings=64,
                    intermediate_size=256)
    model = GPTForCausalLM(cfg)          # TP layers annotate mp shardings
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    dist.shard_optimizer_state(opt, stage=1, axis="sdp")   # ZeRO-1

    step = TrainStep(model, opt, lambda ids, lbl: crit(model(ids), lbl),
                     mesh=mesh, data_axes=("dp",))
    rng = np.random.RandomState(0)
    for i in range(10):
        ids = paddle.to_tensor(rng.randint(0, 256, (8, 32)).astype("int32"))
        loss = step(ids, ids)
        if i % 3 == 0:
            print(f"step {i}: loss={float(loss):.4f} "
                  f"mesh={dict(mesh.shape)}")


if __name__ == "__main__":
    main()
