"""ERNIE-3.0-class toolkit entrypoint (BASELINE.md config table row 5).

Pretrain-style masked-LM + sequence-classification fine-tune on synthetic
data through the SAME fused TrainStep path the flagship uses. Runs on CPU
in under a minute with the tiny default config; pass a preset name for the
real sizes on a TPU host.

Usage: PYTHONPATH=. python examples/train_ernie.py [ernie-3.0-medium]
       PADDLE_TPU_EXAMPLE_TPU=1 ... to use the chips.
"""
import os
import sys

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle


def main():
    from paddle_tpu.models import (ErnieForMaskedLM,
                                   ErnieForSequenceClassification,
                                   ernie_config)
    paddle.seed(0)
    rng = np.random.RandomState(0)

    if len(sys.argv) > 1:
        cfg = ernie_config(sys.argv[1])
        B, S, steps = 8, 512, 20
    else:  # CPU-fast toy config, same code path
        cfg = ernie_config("ernie-3.0-medium", hidden_size=128, num_layers=2,
                           num_heads=2, vocab_size=512,
                           max_position_embeddings=128)
        B, S, steps = 4, 64, 10

    # --- 1) MLM pretrain step (fused chunked loss, no [B,S,V] logits) ---
    mlm = ErnieForMaskedLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=mlm.parameters())
    step = paddle.jit.TrainStep(
        mlm, opt, lambda ids, lbl: mlm.loss(ids, lbl, chunk_size=min(S, 256)))
    ids = rng.randint(0, cfg.vocab_size, (1, B, S)).astype("int32")
    lbl = rng.randint(0, cfg.vocab_size, (1, B, S)).astype("int64")
    losses = step.run_steps(steps, paddle.to_tensor(np.repeat(ids, steps, 0)),
                            paddle.to_tensor(np.repeat(lbl, steps, 0)))
    l = losses.numpy()
    print(f"ERNIE MLM: loss {l[0]:.4f} -> {l[-1]:.4f} over {steps} steps")
    assert np.isfinite(l).all() and l[-1] < l[0]

    # --- 2) sequence-classification fine-tune (toy separable task) ------
    cls = ErnieForSequenceClassification(cfg, num_classes=2)
    copt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                  parameters=cls.parameters())
    import paddle_tpu.nn as nn
    ce = nn.CrossEntropyLoss()
    cstep = paddle.jit.TrainStep(cls, copt,
                                 lambda ids, y: ce(cls(ids), y))
    # label = whether token 7 appears in the first 8 positions
    cids = rng.randint(0, cfg.vocab_size, (steps, B, S)).astype("int32")
    cy = (cids[:, :, :8] == 7).any(-1).astype("int64")
    closs = cstep.run_steps(steps, paddle.to_tensor(cids),
                            paddle.to_tensor(cy)).numpy()
    print(f"ERNIE cls fine-tune: loss {closs[0]:.4f} -> {closs[-1]:.4f}")
    assert np.isfinite(closs).all()
    print("OK")


if __name__ == "__main__":
    main()
