"""Eager + fused-step training example (runs on CPU in seconds).

Usage: PYTHONPATH=. python examples/train_eager.py
"""
import os
import jax

# examples default to CPU so they run anywhere; set PADDLE_TPU_EXAMPLE_TPU=1
# on a TPU host to use the chips
if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn


def main():
    paddle.seed(0)
    X = np.random.randn(512, 16).astype("float32")
    Y = (np.sin(X[:, :1]) + X[:, 1:2] ** 2).astype("float32")

    model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 1))
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    loader = paddle.io.DataLoader(
        paddle.io.TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)]),
        batch_size=64, shuffle=True)

    # eager loop: per-op dispatch, loss.backward() on the tape
    for epoch in range(3):
        for xb, yb in loader:
            loss = nn.MSELoss()(model(xb), yb)
            loss.backward()
            opt.step()
            opt.clear_grad()
        print(f"eager epoch {epoch}: loss={float(loss):.4f}")

    # fused path: the whole step (fwd+bwd+optimizer) is one XLA program
    step = paddle.jit.TrainStep(model, opt,
                                lambda x, y: nn.MSELoss()(model(x), y))
    for i in range(20):
        loss = step(paddle.to_tensor(X[:64]), paddle.to_tensor(Y[:64]))
    print(f"fused step final loss={float(loss):.4f}")


if __name__ == "__main__":
    main()
