"""PP-YOLOE-class toolkit entrypoint (BASELINE.md config table row 5).

Trains the detector (task-aligned assignment + DFL + varifocal loss) on a
synthetic two-box dataset and runs decode (static-shape masked NMS) —
the full train->eval->decode loop a detection-toolkit user runs. CPU-fast
with the lite preset; `ppyoloe-s` on a TPU host.

Usage: PYTHONPATH=. python examples/train_ppyoloe.py [ppyoloe-s]
       PADDLE_TPU_EXAMPLE_TPU=1 ... to use the chips.
"""
import os
import sys

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle


def main():
    from paddle_tpu.vision.models import (yolo_lite, ppyoloe_s, ppyoloe_m,
                                          ppyoloe_l, yolo_loss)
    paddle.seed(0)
    rng = np.random.RandomState(0)

    presets = {"ppyoloe-s": ppyoloe_s, "ppyoloe-m": ppyoloe_m,
               "ppyoloe-l": ppyoloe_l}
    if len(sys.argv) > 1 and sys.argv[1].startswith("ppyoloe"):
        if sys.argv[1] not in presets:
            raise SystemExit(f"unknown preset {sys.argv[1]!r}; "
                             f"choose from {sorted(presets)}")
        model = presets[sys.argv[1]](num_classes=80)
        B, H, steps = 8, 640, 20
    else:
        model = yolo_lite(num_classes=3, width=8)
        B, H, steps = 2, 64, 10
    cfg = model.config

    imgs = rng.randn(B, 3, H, H).astype("float32") * 0.1
    # synthetic ground truth: two boxes per image
    gt_boxes = np.stack([
        np.array([[H * .1, H * .1, H * .5, H * .5],
                  [H * .4, H * .4, H * .9, H * .8]], np.float32)
        for _ in range(B)])
    gt_labels = rng.randint(0, cfg.num_classes, (B, 2)).astype("int64")
    gt_mask = np.ones((B, 2), np.float32)

    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    losses = []
    for i in range(steps):
        outs = model(paddle.to_tensor(imgs))
        loss = yolo_loss(outs, paddle.to_tensor(gt_boxes),
                         paddle.to_tensor(gt_labels),
                         paddle.to_tensor(gt_mask), cfg)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    print(f"PP-YOLOE train: loss {losses[0]:.4f} -> {losses[-1]:.4f} "
          f"over {steps} steps")
    assert np.isfinite(losses).all() and losses[-1] < losses[0]

    model.eval()
    dets = model.decode(paddle.to_tensor(imgs), score_thresh=0.0, max_dets=10)
    boxes, scores, classes = dets[0]
    print(f"decode: {len(scores)} detections on image 0 "
          f"(top score {float(scores[0]) if len(scores) else 0:.3f})")
    print("OK")


if __name__ == "__main__":
    main()
