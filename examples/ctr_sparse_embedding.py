"""Industrial CTR slice: host-RAM sparse embedding PS + dense tower on
device, with optional GeoSGD async mode — the workflow the reference serves
with its brpc parameter server (SURVEY §2.2), redesigned TPU-first
(distributed/ps.py docstring).

Usage: PYTHONPATH=. python examples/ctr_sparse_embedding.py
"""
import os

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed.ps import DistributedEmbedding, GeoSGDEmbedding


def main(geo: bool = False):
    paddle.seed(0)
    dim, vocab = 16, 100_000  # rows materialize on first touch — no 100k alloc
    emb_cls = GeoSGDEmbedding if geo else DistributedEmbedding
    kwargs = {"geo_step": 8} if geo else {"optimizer": "adagrad"}
    emb = emb_cls(dim=dim, num_shards=4, lr=0.05, **kwargs)

    tower = nn.Sequential(nn.Linear(3 * dim, 64), nn.ReLU(), nn.Linear(64, 1))
    opt = paddle.optimizer.Adam(parameters=tower.parameters(),
                                learning_rate=1e-3)
    bce = nn.BCEWithLogitsLoss()

    rng = np.random.RandomState(0)
    # synthetic CTR: 3 slots (user/item/context), click depends on item ids
    for step in range(60):
        ids = rng.zipf(1.5, (256, 3)).clip(0, vocab - 1).astype("int64")
        clicks = ((ids[:, 1] % 7) < 2).astype("float32").reshape(-1, 1)
        feats = emb(paddle.to_tensor(ids))                  # [256, 3, dim]
        x = paddle.reshape(feats, [256, 3 * dim])
        loss = bce(tower(x), paddle.to_tensor(clicks))
        loss.backward()
        opt.step()
        opt.clear_grad()
        if step % 20 == 0:
            print(f"step {step:3d}  loss {float(loss):.4f}  "
                  f"rows {emb.state_size()}")
    if geo:
        emb.sync()
    print(f"final loss {float(loss):.4f}; touched rows: {emb.state_size()} "
          f"of {vocab} (insert-on-touch)")


def main_heter(steps: int = 120, batch: int = 256):
    """Device-cached tier (distributed/heter.py — the HeterPS answer): hot
    rows live in HBM, prefetch overlaps admission with the step, and the
    only host traffic is the miss set. Prints measured throughput."""
    import time
    from paddle_tpu.distributed.heter import MeshShardedEmbedding

    paddle.seed(0)
    dim, vocab = 16, 100_000
    emb = MeshShardedEmbedding(dim=dim, capacity=1 << 13, lr=0.05)
    tower = nn.Sequential(nn.Linear(3 * dim, 64), nn.ReLU(), nn.Linear(64, 1))
    opt = paddle.optimizer.Adam(parameters=tower.parameters(),
                                learning_rate=1e-3)
    bce = nn.BCEWithLogitsLoss()
    rng = np.random.RandomState(0)

    def batch_ids():
        return rng.zipf(1.5, (batch, 3)).clip(0, vocab - 1).astype("int64")

    ids = batch_ids()
    warmup = min(19, max(0, steps - 2))
    t0 = None
    for step in range(steps):
        nxt = batch_ids()
        emb.prefetch(nxt)                      # overlap admission with step
        feats = emb(paddle.to_tensor(ids))
        x = paddle.reshape(feats, [batch, 3 * dim])
        clicks = ((ids[:, 1] % 7) < 2).astype("float32").reshape(-1, 1)
        loss = bce(tower(x), paddle.to_tensor(clicks))
        loss.backward()
        opt.step()
        opt.clear_grad()
        ids = nxt
        if step == warmup:
            t0 = time.perf_counter()           # skip warmup/compile
    dt = max(time.perf_counter() - t0, 1e-9)
    n = steps - warmup - 1
    print(f"heter tier: loss {float(loss):.4f}  rows {emb.state_size()} "
          f"(resident {emb.resident_rows()})  "
          f"{n * batch / dt:,.0f} examples/s  "
          f"{n * batch * 3 / dt:,.0f} lookups/s")


if __name__ == "__main__":
    print("== sync adagrad PS ==")
    main(geo=False)
    print("== GeoSGD async ==")
    main(geo=True)
    print("== device-cached heter tier ==")
    main_heter()
