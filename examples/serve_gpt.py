"""GPT serving walkthrough: the full static-serving matrix in one script.

Every path compiles ONCE and replays with fixed shapes (the TPU-native
analog of the reference's fused_multi_transformer CacheKV serving):

  1. generate_static          — one-shot: prefill + decode in ONE program
  2. generate_static_ragged   — ANY prompt length <= cap, one executable
  3. weight_dtype="int8"      — Pallas in-register-dequant GEMM weights
  4. cache_dtype="int8"       — int8 KV cache, factored-scale attention
  5. prefill_static/decode_static — shared prefix paid ONCE, N samples
     (composes with ragged prompts and both int8 knobs)
  6. ServingEngine — request-level continuous batching over the same
     executables, driven by open-loop synthetic traffic, ending in the
     real /metrics payload a frontend scrapes (TTFT/TPOT/e2e histograms,
     queue/batch/KV gauges, zero-recompile steady state)
  7. the telemetry SERVER (obs, ISSUE 12) — the same engine scraped over
     HTTP: `curl /metrics` (collision-checked Prometheus page),
     `/healthz` (the autoscaler inputs: drain state + queue depth +
     overloaded_total; HTTP 503 once begin_drain() flips the replica
     out of rotation), `/statusz`, and `/tracez` tail-sampled traces

Usage: PYTHONPATH=. python examples/serve_gpt.py
       PADDLE_TPU_EXAMPLE_TPU=1 ... [gpt3-1.3b] for real-chip sizes.
"""
import os
import sys

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle


def main():
    from paddle_tpu.models import GPTForCausalLM, gpt_config, GPTConfig
    paddle.seed(0)
    if len(sys.argv) > 1:
        cfg = gpt_config(sys.argv[1])
        B, cap, new = 8, 128, 32
    else:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=96,
                        intermediate_size=128)
        B, cap, new = 2, 12, 8
    model = GPTForCausalLM(cfg)
    if os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        model.to(dtype="bfloat16")
    model.eval()
    rng = np.random.RandomState(0)

    # 1. one-shot fixed-length serving
    ids = paddle.to_tensor(rng.randint(1, cfg.vocab_size,
                                       (B, cap)).astype("int64"))
    out = model.generate_static(ids, max_new_tokens=new)
    print("one-shot:", out.shape)

    # 2. ragged prompts (right-padded; ONE executable serves any lengths)
    lens = [max(1, cap - 2 - i) for i in range(B)]
    r = model.generate_static_ragged(ids, lens, max_new_tokens=new)
    print("ragged:", r.shape, "lens:", lens)

    # 3+4. quantized serving: int8 weights + int8 KV cache
    q = model.generate_static(ids, max_new_tokens=new,
                              weight_dtype="int8", cache_dtype="int8")
    agree = float((q.numpy()[:, cap:] == out.numpy()[:, cap:]).mean())
    print(f"int8 weights+KV: greedy agreement {agree:.3f}")

    # 5. prefix reuse: one prefill, many sampled continuations
    st = model.prefill_static(ids, max_len=cap + new)
    greedy = model.decode_static(st, max_new_tokens=new)
    assert (greedy.numpy() == out.numpy()[:, cap:]).all()
    samples = [model.decode_static(st, max_new_tokens=new,
                                   temperature=0.9, seed=s).numpy()
               for s in range(3)]
    print("prefix-reuse: greedy tail parity OK;",
          len({s.tobytes() for s in samples}), "distinct samples")

    # 5b. ragged + prefix reuse compose
    str_ = model.prefill_static(ids, max_len=cap + new, prompt_lens=lens)
    dr = model.decode_static(str_, max_new_tokens=new)
    assert (dr.numpy() == r.numpy()[:, cap:]).all()
    print("ragged prefix-reuse: per-row greedy parity OK")

    # 6. launch-level stats: a StepMonitor bracketing live decode launches —
    # steady tokens/s, device memory, and the recompile counter (a
    # shape-unstable serving loop shows up here immediately).
    from paddle_tpu.profiler import StepMonitor
    mon = StepMonitor(unit="tokens/s")
    for _ in range(3):
        with mon.step(items=B * new):
            out = model.generate_static(ids, max_new_tokens=new)
            _ = out.numpy()
    print(mon.metrics_text(), end="")

    # 7. request-level serving: the ServingEngine admits ragged prompts
    # into a bounded queue, assembles fixed-shape micro-batches and drives
    # the SAME prefill/decode executables — now with per-request traces
    # (enqueue→admit→prefill→first-token→finish), TTFT/TPOT/e2e latency
    # histograms and queue/batch/KV gauges. Open-loop synthetic traffic:
    # arrivals follow their own schedule regardless of service speed, so
    # queue wait is a real measurement, not an artifact of the replayer.
    from paddle_tpu.inference import (ServingEngine, ServingConfig,
                                      synthetic_traffic)
    engine = ServingEngine(model, ServingConfig(
        max_batch=B, prompt_cap=cap, max_new_tokens=new,
        decode_chunk=max(1, new // 2)))
    # boot the ops surface FIRST (ISSUE 12) — a real replica's telemetry
    # server is up before traffic lands, so /tracez sees every request
    srv = engine.serve_telemetry()
    traffic = synthetic_traffic(4 * B, prompt_cap=cap,
                                vocab_size=cfg.vocab_size, rate=200.0,
                                seed=3, min_len=max(1, cap // 3))
    import time
    t0 = engine.clock()
    finished = []
    for item in traffic:
        wait = t0 + item["at"] - engine.clock()
        if wait > 0:
            time.sleep(wait)                    # arrivals keep schedule
        engine.submit(item["prompt"], enqueue_at=t0 + item["at"])
        if engine.queue_depth >= B:
            finished += engine.step()           # serve while traffic lands
    finished += engine.drain()
    n_ok = sum(1 for r in finished if r.status == "done")
    s = engine.summary()
    print(f"engine: {n_ok} requests over {s['batches_total']} batches, "
          f"fill {s['batch_fill_ratio']:.2f}, "
          f"kv occupancy {s['kv_occupancy']:.2f} (true tokens)")
    if s.get("ttft_seconds"):
        print(f"TTFT p50/p99: {s['ttft_seconds']['p50'] * 1e3:.1f} / "
              f"{s['ttft_seconds']['p99'] * 1e3:.1f} ms")
    assert s["batch_step"]["recompiles"] == 0   # steady loop never reshapes

    # 8. the ops surface over the wire (ISSUE 12): what a router /
    # autoscaler / dashboard actually scrapes. serve_telemetry() wires
    # /metrics (unified registry), /healthz, /statusz and /tracez around
    # the live engine on an ephemeral port — this is the in-process
    # `curl`, byte-for-byte what the network sees.
    import json as _json
    from urllib.request import urlopen
    from urllib.error import HTTPError
    print(f"---- telemetry server on {srv.url()} ----")
    metrics = urlopen(srv.url("/metrics")).read().decode()
    print(f"$ curl /metrics        -> {len(metrics.splitlines())} lines, "
          f"e.g.:")
    for line in metrics.splitlines():
        if line.startswith("paddle_tpu_serving_ttft_seconds_count") or \
                line.startswith("paddle_tpu_serving_completed_total"):
            print(f"    {line}")
    health = _json.loads(urlopen(srv.url("/healthz")).read())
    print(f"$ curl /healthz        -> 200 {health}")
    tz = _json.loads(urlopen(srv.url("/tracez?order=slowest&limit=1")).read())
    print(f"$ curl /tracez         -> {tz['summary']['retained']} traces "
          f"retained (tail-sampled), slowest trace_id "
          f"{tz['traces'][0]['trace_id']}")
    # graceful drain flips the replica out of rotation: /healthz turns
    # 503/"draining" the moment begin_drain() runs — the load balancer
    # ejects it while in-flight work finishes
    engine.begin_drain()
    try:
        urlopen(srv.url("/healthz"))
        raise AssertionError("draining replica must fail its health check")
    except HTTPError as e:
        print(f"$ curl /healthz        -> {e.code} "
              f"{_json.loads(e.read())['status']} (after begin_drain)")
    engine.drain(seal=True)
    srv.close()
    engine.resume_admission()

    print("---- /metrics ----")
    print(engine.metrics_text(), end="")
    print("OK")


if __name__ == "__main__":
    main()
