"""GPT text generation example: eager growing-cache vs compiled static-cache.

Shows the two decode paths and why serving wants the static one:
`generate()` re-traces at every new sequence length (fine eagerly),
`generate_static()` compiles prefill + the whole decode loop ONCE
(fixed KV buffers + lax.scan) — 1571 tokens/s/chip at GPT-1.3B B=8 on v5e.

Usage: PYTHONPATH=. python examples/generate_gpt.py
       PADDLE_TPU_EXAMPLE_TPU=1 ... [gpt3-1.3b] to decode big on the chips.
"""
import os
import sys
import time

import jax

if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle


def main():
    from paddle_tpu.models import GPTForCausalLM, gpt_config
    paddle.seed(0)

    if len(sys.argv) > 1:
        cfg = gpt_config(sys.argv[1])
        B, p_len, new = 8, 128, 64
    else:
        cfg = gpt_config("gpt3-125m", hidden_size=128, num_layers=2,
                         num_heads=2, vocab_size=512,
                         max_position_embeddings=256)
        B, p_len, new = 2, 16, 16

    model = GPTForCausalLM(cfg)
    if os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        model.to(dtype="bfloat16")
    model.eval()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (B, p_len)).astype("int64"))

    out_a = model.generate(ids, max_new_tokens=new)          # eager, growing
    t0 = time.perf_counter()
    out_b = model.generate_static(ids, max_new_tokens=new)   # one program
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out_b = model.generate_static(ids, max_new_tokens=new)   # cached runner
    run_s = time.perf_counter() - t0

    if os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
        # bf16 cache dtypes differ between the two paths (f32 growing
        # cache vs bf16 static buffers) — a rounding flip on an argmax tie
        # is possible over long greedy runs, so report instead of assert
        agree = float((out_a.numpy() == out_b.numpy()).mean())
        print(f"greedy agreement (bf16 paths): {agree:.3f}")
    else:
        assert (out_a.numpy() == out_b.numpy()).all(), "greedy parity violated"
        print(f"greedy parity OK over {new} tokens")
    print(f"static path: {compile_s:.1f}s first call (compile), "
          f"{run_s * 1e3:.0f} ms after ({B * new / run_s:.0f} tokens/s)")

    # temperature sampling through the same compiled path
    sampled = model.generate_static(ids, max_new_tokens=new, temperature=0.8,
                                    seed=1)
    print("sampled tail:", sampled.numpy()[0, -8:].tolist())

    # quantized serving: int8 weights stream through the Pallas
    # dequant-in-register GEMM; the int8 KV cache halves decode's KV
    # bandwidth (factored-scale attention). Near-greedy-parity, not
    # bit-exact — weights AND cached K/V are quantized.
    q = model.generate_static(ids, max_new_tokens=new,
                              weight_dtype="int8", cache_dtype="int8")
    agree_q = float((q.numpy()[:, -new:] == out_b.numpy()[:, -new:]).mean())
    base_dt = "bf16" if os.environ.get("PADDLE_TPU_EXAMPLE_TPU") else "f32"
    print(f"int8 weights+KV-cache greedy agreement vs {base_dt}: "
          f"{agree_q:.3f}")
    print("OK")


if __name__ == "__main__":
    main()
