"""Train → export → serve: the deployment path.

Usage: PYTHONPATH=. python examples/deploy_inference.py
"""
import os
import jax

# examples default to CPU so they run anywhere; set PADDLE_TPU_EXAMPLE_TPU=1
# on a TPU host to use the chips
if not os.environ.get("PADDLE_TPU_EXAMPLE_TPU"):
    jax.config.update("jax_platforms", "cpu")

import tempfile

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference


def main():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 4))
    model.eval()

    prefix = tempfile.mkdtemp() + "/model"
    # dynamic batch dim -> one artifact serves any batch size
    paddle.jit.save(model, prefix,
                    input_spec=[paddle.jit.InputSpec([None, 8], "float32")])

    config = inference.Config(prefix)
    predictor = inference.create_predictor(config)
    print("inputs:", predictor.get_input_names())
    for bs in (1, 5, 17):
        (out,) = predictor.run([np.random.randn(bs, 8).astype("float32")])
        print(f"batch {bs}: output {out.shape}")


if __name__ == "__main__":
    main()
